"""Batched ed25519 verification on TPU: the hot compute path.

Design (TPU-first; replaces the reference's per-call libsodium
`crypto_sign_verify_detached`, /root/reference/src/crypto/SecretKey.cpp:332):

- Verification equation (RFC 8032, cofactorless — matching the OpenSSL CPU
  backend semantics exactly): [S]B == R + [k]A with k = SHA512(R‖A‖M) mod L.
  We compute Q = [S]B + [k](−A) on-device and compare with the decompressed
  R projectively (no inversion).
- The batch axis is the parallelism: every step below is a fused vector op
  over the whole batch; scalar control flow is eliminated (fori_loop with
  static trip counts, masked table selects instead of branches).
- Host does the byte-level work that TPUs are bad at: SHA-512 (tiny
  messages), canonicality prechecks (S < L, y < p), bit-slicing keys into
  13-bit limbs and scalars into 4-bit windows.
- Fixed-base [S]B uses a precomputed 64×16 radix-16 table of B multiples in
  Niels form (y+x, y−x, 2dxy): 64 masked-lookup additions, zero doublings.
- Variable-base [k](−A) builds a per-item 16-entry extended-coordinate
  table (15 additions) then runs 63 iterations of 4 doublings + 1 table
  addition inside a fori_loop.
- Point formulas: extended coordinates, a=−1 twisted Edwards unified
  add/double (complete on the prime-order subgroup).

A pure-Python (int) implementation lives alongside for table generation and
as a test oracle.
"""

from __future__ import annotations

import hashlib
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from .field import (
    NLIMBS, LIMB_BITS, LIMB_MASK, P, fe_add, fe_carry, fe_eq, fe_freeze,
    fe_is_zero, fe_mul, fe_mul_small, fe_neg, fe_one, fe_parity, fe_pow_p58,
    fe_sq, fe_sub, fe_zero, int_from_limbs, limbs_from_int,
)

# --- curve constants (python ints) ----------------------------------------

L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
B_Y = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """Python-int point decompression (RFC 8032 §5.1.3 math)."""
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


B_X = _recover_x(B_Y, 0)


class _Pt:
    """Python-int extended-coordinate point (oracle + table generation)."""

    __slots__ = ("x", "y", "z", "t")

    def __init__(self, x, y, z=1, t=None):
        self.x, self.y, self.z = x % P, y % P, z % P
        self.t = (x * y * pow(z, P - 2, P)) % P if t is None else t % P

    @classmethod
    def identity(cls):
        return cls(0, 1, 1, 0)

    def add(self, o: "_Pt") -> "_Pt":
        a = (self.y - self.x) * (o.y - o.x) % P
        b = (self.y + self.x) * (o.y + o.x) % P
        c = self.t * D2 % P * o.t % P
        d = 2 * self.z * o.z % P
        e, f, g, h = b - a, d - c, d + c, b + a
        return _Pt(e * f % P, g * h % P, f * g % P, e * h % P)

    def dbl(self) -> "_Pt":
        a = self.x * self.x % P
        b = self.y * self.y % P
        c = 2 * self.z * self.z % P
        h = a + b
        e = h - (self.x + self.y) ** 2 % P
        g = a - b
        f = c + g
        return _Pt(e * f % P, g * h % P, f * g % P, e * h % P)

    def mul(self, n: int) -> "_Pt":
        q = _Pt.identity()
        p = self
        while n:
            if n & 1:
                q = q.add(p)
            p = p.dbl()
            n >>= 1
        return q

    def affine(self) -> tuple[int, int]:
        zi = pow(self.z, P - 2, P)
        return (self.x * zi % P, self.y * zi % P)

    def compress(self) -> bytes:
        x, y = self.affine()
        return int.to_bytes(y | ((x & 1) << 255), 32, "little")


B_POINT = _Pt(B_X, B_Y)


def verify_oracle(pub: bytes, sig: bytes, msg: bytes) -> bool:
    """Pure-Python RFC 8032 cofactorless verify — the semantics oracle both
    backends must match."""
    if len(pub) != 32 or len(sig) != 64:
        return False
    r_bytes, s_bytes = sig[:32], sig[32:]
    s = int.from_bytes(s_bytes, "little")
    if s >= L:
        return False
    ay = int.from_bytes(pub, "little")
    a_sign, ay = ay >> 255, ay & ((1 << 255) - 1)
    ry = int.from_bytes(r_bytes, "little")
    r_sign, ry = ry >> 255, ry & ((1 << 255) - 1)
    ax = _recover_x(ay, a_sign)
    rx = _recover_x(ry, r_sign)
    if ax is None or rx is None:
        return False
    k = int.from_bytes(hashlib.sha512(r_bytes + pub + msg).digest(),
                       "little") % L
    a_neg = _Pt(P - ax if ax else 0, ay)
    q = B_POINT.mul(s).add(a_neg.mul(k))  # [S]B − [k]A
    qx, qy = q.affine()
    return qx == rx and qy == ry


# --- precomputed fixed-base table (Niels form) -----------------------------

def _build_fixed_table() -> np.ndarray:
    """table[j, v] = Niels(v · 16^j · B) as 3×20 limbs: (y+x, y−x, 2dxy)."""
    tab = np.zeros((64, 16, 3, NLIMBS), np.int32)
    base = B_POINT
    for j in range(64):
        acc = _Pt.identity()
        for v in range(16):
            x, y = acc.affine() if v else (0, 1)
            tab[j, v, 0] = limbs_from_int((y + x) % P)
            tab[j, v, 1] = limbs_from_int((y - x) % P)
            tab[j, v, 2] = limbs_from_int(2 * D * x % P * y % P)
            acc = acc.add(base)
        for _ in range(4):
            base = base.dbl()
    return tab


_FIXED_TABLE: np.ndarray | None = None


def fixed_table() -> np.ndarray:
    global _FIXED_TABLE
    if _FIXED_TABLE is None:
        _FIXED_TABLE = _build_fixed_table()
    return _FIXED_TABLE


# --- jax point ops (points = (X, Y, Z, T) stacked as (..., 4, 20)) ---------

def pt_identity(batch_shape=()) -> jnp.ndarray:
    return jnp.stack([fe_zero(batch_shape), fe_one(batch_shape),
                      fe_one(batch_shape), fe_zero(batch_shape)], axis=-2)


_D2_LIMBS = limbs_from_int(D2)
_SQRT_M1_LIMBS = limbs_from_int(SQRT_M1)
_D_LIMBS = limbs_from_int(D)


def pt_add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Unified a=−1 extended addition (add-2008-hwcd-3)."""
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    x2, y2, z2, t2 = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    a = fe_mul(fe_sub(y1, x1), fe_sub(y2, x2))
    b = fe_mul(fe_add(y1, x1), fe_add(y2, x2))
    c = fe_mul(fe_mul(t1, jnp.asarray(_D2_LIMBS)), t2)
    d = fe_mul_small(fe_mul(z1, z2), 2)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    return jnp.stack([fe_mul(e, f), fe_mul(g, h),
                      fe_mul(f, g), fe_mul(e, h)], axis=-2)


def pt_add_folded(p: jnp.ndarray, q: jnp.ndarray,
                  need_t: bool = False) -> jnp.ndarray:
    """Extended add where q's T row is pre-multiplied by 2d (table form).
    Ladder adds feed doublings, which never read T, so by default the
    output T (the e·h multiply) is skipped; the final window add passes
    need_t=True because the fixed-base Niels chain reads it."""
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    x2, y2, z2, t2d = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    a = fe_mul(fe_sub(y1, x1), fe_sub(y2, x2))
    b = fe_mul(fe_add(y1, x1), fe_add(y2, x2))
    c = fe_mul(t1, t2d)
    d = fe_mul_small(fe_mul(z1, z2), 2)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    t = fe_mul(e, h) if need_t else fe_zero(x1.shape[:-1])
    return jnp.stack([fe_mul(e, f), fe_mul(g, h),
                      fe_mul(f, g), t], axis=-2)


def pt_add_niels(p: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Mixed addition with a precomputed Niels point (y+x, y−x, 2dxy)."""
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    ypx, ymx, xy2d = n[..., 0, :], n[..., 1, :], n[..., 2, :]
    a = fe_mul(fe_sub(y1, x1), ymx)
    b = fe_mul(fe_add(y1, x1), ypx)
    c = fe_mul(t1, xy2d)
    d = fe_mul_small(z1, 2)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    return jnp.stack([fe_mul(e, f), fe_mul(g, h),
                      fe_mul(f, g), fe_mul(e, h)], axis=-2)


def pt_dbl(p: jnp.ndarray, need_t: bool = True) -> jnp.ndarray:
    """a=−1 extended doubling (dbl-2008-hwcd). Doubling never READS the
    T coordinate, so ladder doublings whose output feeds another doubling
    pass need_t=False and skip the e·h multiply (3 of every 4 ladder
    steps)."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fe_sq(x1)
    b = fe_sq(y1)
    c = fe_mul_small(fe_sq(z1), 2)
    h = fe_add(a, b)
    e = fe_sub(h, fe_sq(fe_add(x1, y1)))
    g = fe_sub(a, b)
    f = fe_add(c, g)
    t = fe_mul(e, h) if need_t else fe_zero(x1.shape[:-1])
    return jnp.stack([fe_mul(e, f), fe_mul(g, h),
                      fe_mul(f, g), t], axis=-2)


def pt_neg(p: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([fe_neg(p[..., 0, :]), p[..., 1, :],
                      p[..., 2, :], fe_neg(p[..., 3, :])], axis=-2)


def fe_decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """Decompress (y, sign) → (x, ok). y is canonical (host-checked y < p).

    x = sqrt((y²−1)/(dy²+1)); multiply by sqrt(−1) when the first candidate
    fails; reject when neither squares to the target or x=0 with sign=1.
    """
    one = fe_one(y_limbs.shape[:-1])
    y2 = fe_sq(y_limbs)
    u = fe_sub(y2, one)
    v = fe_add(fe_mul(y2, jnp.asarray(_D_LIMBS)), one)
    v3 = fe_mul(fe_sq(v), v)
    v7 = fe_mul(fe_sq(v3), v)
    x = fe_mul(fe_mul(u, v3), fe_pow_p58(fe_mul(u, v7)))
    vx2 = fe_mul(v, fe_sq(x))
    ok1 = fe_eq(vx2, u)
    ok2 = fe_eq(vx2, fe_neg(u))
    x_alt = fe_mul(x, jnp.asarray(_SQRT_M1_LIMBS))
    x = jnp.where(ok2[..., None] & ~ok1[..., None], x_alt, x)
    ok = ok1 | ok2
    x_is_zero = fe_is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    # fix parity
    flip = (fe_parity(x) != sign)
    x = jnp.where(flip[..., None], fe_neg(x), x)
    return x, ok


def _select16(table: jnp.ndarray, nib: jnp.ndarray) -> jnp.ndarray:
    """Constant-shape 16-way select: table (..., 16, K, 20), nib (...,).
    A masked sum instead of a gather — XLA fuses it into vector selects."""
    oh = (jnp.arange(16, dtype=jnp.int32) ==
          nib[..., None]).astype(jnp.int32)           # (..., 16)
    return jnp.sum(table * oh[..., :, None, None], axis=-3)


def verify_kernel(ay: jnp.ndarray, a_sign: jnp.ndarray,
                  ry: jnp.ndarray, r_sign: jnp.ndarray,
                  s_nibs: jnp.ndarray, k_nibs: jnp.ndarray) -> jnp.ndarray:
    """Batched verify core. All inputs int32:
    ay, ry: (B, 20) canonical y limbs; a_sign, r_sign: (B,);
    s_nibs, k_nibs: (B, 64) radix-16 digits of S (LSB-first) and
    k = SHA512(R‖A‖M) mod L (LSB-first). Returns (B,) bool.
    """
    batch = ay.shape[:-1]

    ax, a_ok = fe_decompress(ay, a_sign)
    rx, r_ok = fe_decompress(ry, r_sign)

    # A in extended coords, negated: Q = [S]B + [k](−A)
    neg_ax = fe_neg(ax)
    neg_at = fe_neg(fe_mul(ax, ay))
    a_pt = jnp.stack([neg_ax, ay, fe_one(batch), neg_at], axis=-2)

    # per-item table of v·(−A), v = 0..15, extended coords: (B, 16, 4, 20);
    # entry T is pre-multiplied by 2d so the ladder add does c = T1·(2d·T2)
    # in ONE multiply (Niels-style T folding)
    entries = [pt_identity(batch), a_pt]
    for v in range(2, 16):
        if v % 2 == 0:
            entries.append(pt_dbl(entries[v // 2]))
        else:
            entries.append(pt_add(entries[v - 1], a_pt))
    d2 = jnp.asarray(_D2_LIMBS)
    folded = [jnp.concatenate(
        [e[..., :3, :], fe_mul(e[..., 3, :], d2)[..., None, :]], axis=-2)
        for e in entries]
    a_table = jnp.stack(folded, axis=-3)

    # variable-base: MSB-first over 64 nibbles of k. The window add's T
    # output is never read (the next 4 doublings ignore T; the 4th
    # doubling regenerates it), so the add also skips its e·h multiply.
    def vb_window(q, nib, need_t):
        q = pt_dbl(q, need_t=False)
        q = pt_dbl(q, need_t=False)
        q = pt_dbl(q, need_t=False)
        q = pt_dbl(q, need_t=True)
        return pt_add_folded(q, _select16(a_table, nib), need_t=need_t)

    def vb_body(i, q):
        return vb_window(q, k_nibs[..., 63 - i], False)

    q = jax.lax.fori_loop(0, 63, vb_body, pt_identity(batch))
    # final window peeled: its add DOES produce T, which the fixed-base
    # Niels chain below consumes
    q = vb_window(q, k_nibs[..., 0], True)

    # fixed-base: Σ_j table[j][s_nib_j], 64 Niels additions, no doublings
    ftab = jnp.asarray(fixed_table())  # (64, 16, 3, 20)

    def fb_body(j, acc):
        row = jax.lax.dynamic_index_in_dim(ftab, j, axis=0,
                                           keepdims=False)  # (16, 3, 20)
        nib = s_nibs[..., j]
        oh = (jnp.arange(16, dtype=jnp.int32) ==
              nib[..., None]).astype(jnp.int32)
        sel = jnp.sum(row * oh[..., :, None, None], axis=-3)
        return pt_add_niels(acc, sel)

    q = jax.lax.fori_loop(0, 64, fb_body, q)

    # projective compare with affine R: X == rx·Z and Y == ry·Z
    xq, yq, zq = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    eq = fe_eq(xq, fe_mul(rx, zq)) & fe_eq(yq, fe_mul(ry, zq))
    return a_ok & r_ok & eq


# --- host-side batch preparation ------------------------------------------

_BYTE_SHIFTS = None


def bytes_to_limbs_np(b: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 → (B, 20) int32 13-bit limbs (little-endian value)."""
    x = b.astype(np.int64)
    out = np.zeros((*b.shape[:-1], NLIMBS), np.int64)
    for i in range(NLIMBS):
        bit = LIMB_BITS * i
        k, r = bit >> 3, bit & 7
        v = x[..., k] >> r
        if k + 1 < 32:
            v = v | (x[..., k + 1] << (8 - r))
        if k + 2 < 32:
            v = v | (x[..., k + 2] << (16 - r))
        out[..., i] = v & LIMB_MASK
    return out.astype(np.int32)


def scalar_to_nibs(s: int) -> np.ndarray:
    return np.array([(s >> (4 * j)) & 15 for j in range(64)], np.int32)


def prepare_batch(pubs: list[bytes], sigs: list[bytes],
                  msgs: list[bytes]) -> dict:
    """Host preprocessing: hashing, canonicality prechecks, bit-slicing.
    Returns device-ready int32 arrays + a host-side precheck mask."""
    n = len(pubs)
    ay = np.zeros((n, 32), np.uint8)
    ry = np.zeros((n, 32), np.uint8)
    a_sign = np.zeros(n, np.int32)
    r_sign = np.zeros(n, np.int32)
    s_nibs = np.zeros((n, 64), np.int32)
    k_nibs = np.zeros((n, 64), np.int32)
    pre_ok = np.zeros(n, bool)
    for i, (pub, sig, msg) in enumerate(zip(pubs, sigs, msgs)):
        if len(pub) != 32 or len(sig) != 64:
            continue
        s = int.from_bytes(sig[32:], "little")
        ayi = int.from_bytes(pub, "little")
        ryi = int.from_bytes(sig[:32], "little")
        a_sign[i], ayv = ayi >> 255, ayi & ((1 << 255) - 1)
        r_sign[i], ryv = ryi >> 255, ryi & ((1 << 255) - 1)
        if s >= L or ayv >= P or ryv >= P:
            continue
        pre_ok[i] = True
        ay[i] = np.frombuffer(
            ayv.to_bytes(32, "little"), np.uint8)
        ry[i] = np.frombuffer(
            ryv.to_bytes(32, "little"), np.uint8)
        s_nibs[i] = scalar_to_nibs(s)
        k = int.from_bytes(
            hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % L
        k_nibs[i] = scalar_to_nibs(k)
    return {
        "ay": bytes_to_limbs_np(ay), "a_sign": a_sign,
        "ry": bytes_to_limbs_np(ry), "r_sign": r_sign,
        "s_nibs": s_nibs, "k_nibs": k_nibs, "pre_ok": pre_ok,
    }


@partial(jax.jit, static_argnames=())
def verify_batch_jit(ay, a_sign, ry, r_sign, s_nibs, k_nibs):
    return verify_kernel(ay, a_sign, ry, r_sign, s_nibs, k_nibs)


def verify_batch(pubs: list[bytes], sigs: list[bytes],
                 msgs: list[bytes]) -> np.ndarray:
    """End-to-end batched verify (host prep + device kernel)."""
    prep = prepare_batch(pubs, sigs, msgs)
    ok = np.asarray(verify_batch_jit(
        jnp.asarray(prep["ay"]), jnp.asarray(prep["a_sign"]),
        jnp.asarray(prep["ry"]), jnp.asarray(prep["r_sign"]),
        jnp.asarray(prep["s_nibs"]), jnp.asarray(prep["k_nibs"])))
    return ok & prep["pre_ok"]
