"""Rule implementations for sctlint (see package docstring for the
rule catalog).

Two phases: `ModuleFacts` is a single AST walk per module collecting
everything every rule needs (clock reads, randomness, except-pass
handlers, fault-site literals, metric literals, function defs + their
direct calls, thread entry points, `@main_thread_only` marks); the
`rule_*` functions then turn facts — some per-module, some whole-tree
(T1's call-graph walk, F1/M1's registry and doc cross-checks) — into
findings.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import Finding

# clock-reading attributes on the `time` module (time.sleep is a pacing
# call, not a clock read — the VirtualClock contract covers scheduling
# separately)
_TIME_READS = {"time", "monotonic", "perf_counter", "perf_counter_ns",
               "monotonic_ns", "time_ns", "process_time", "clock"}
_DATETIME_READS = {"now", "utcnow", "today", "fromtimestamp"}
# random-module attributes that are NOT the unseeded global stream
_RANDOM_OK = {"Random", "SystemRandom", "seed"}
_METRIC_CALLS = {"new_counter", "new_gauge", "new_meter", "new_timer",
                 "new_histogram"}
_FAULT_CALLS = {"should_fire", "fire_point"}

# method names too generic to follow across objects in the T1 walk:
# `tmp.close()` / `sock.send()` / `thread.start()` resolving by bare
# name into unrelated package defs produced chains like
# `_cc_build -> close -> remove_transport -> ... -> recv_scp_envelope`.
# A name on this list is still followed for `self.X()` / bare `X()`
# calls (same-module resolution), and a *marked* function name always
# triggers regardless — the stoplist only prunes cross-object breadth.
_GENERIC_ATTRS = {
    "close", "send", "sendall", "recv", "accept", "connect", "start",
    "stop", "run", "join", "wake", "write", "read", "flush", "commit",
    "rollback", "execute", "executemany", "fetchone", "fetchall",
    "get", "put", "pop", "append", "appendleft", "popleft", "add",
    "remove", "discard", "clear", "update", "set", "setdefault",
    "cancel", "acquire", "release", "submit", "shutdown", "mark",
    "result", "done", "items", "keys", "values", "copy", "extend",
    "sort", "split", "strip", "encode", "decode", "hex", "digest",
    "info", "debug", "warning", "error", "exception", "sleep", "wait",
    "notify", "unlink", "exists", "makedirs",
}


class FuncInfo:
    """One function/method def: identity plus its DIRECT calls (nested
    defs are separate FuncInfos — their bodies run on whatever thread
    eventually calls them, not on their parent's). Calls are
    (kind, name) with kind `bare` (f()), `self` (self.f()) or `attr`
    (obj.f()) — resolution precision differs per kind."""

    __slots__ = ("path", "qualname", "name", "line", "calls", "marked")

    def __init__(self, path: str, qualname: str, name: str,
                 line: int) -> None:
        self.path = path
        self.qualname = qualname
        self.name = name
        self.line = line
        self.calls: Set[Tuple[str, str]] = set()
        self.marked = False            # @main_thread_only


class ThreadEntry:
    """A function handed to a worker: Thread(target=X) / executor.submit(X).
    `func_name` resolves against FuncInfo names; for lambdas the calls
    are inlined."""

    __slots__ = ("path", "line", "func_kind", "func_name", "inline_calls",
                 "via")

    def __init__(self, path: str, line: int, func_kind: str,
                 func_name: Optional[str],
                 inline_calls: Optional[Set[Tuple[str, str]]],
                 via: str) -> None:
        self.path = path
        self.line = line
        self.func_kind = func_kind
        self.func_name = func_name
        self.inline_calls = inline_calls or set()
        self.via = via


class ModuleFacts(ast.NodeVisitor):
    """Single-pass fact collector for one module."""

    def __init__(self, path: str, tree: ast.AST) -> None:
        self.path = path
        # import bindings: local name -> canonical ("time", "datetime",
        # "random", "os") for module imports; ("time", "perf_counter")
        # style tuples for from-imports of flagged names
        self.module_alias: Dict[str, str] = {}
        self.from_bind: Dict[str, Tuple[str, str]] = {}

        self.imported_names: Set[str] = set()

        self.clock_uses: List[Tuple[int, str, str]] = []   # line, expr, qual
        self.random_uses: List[Tuple[int, str, str]] = []
        self.except_passes: List[Tuple[int, str, str]] = []  # line, kind, qual
        self.fault_literals: List[Tuple[int, str, str]] = []  # line, site, qual
        self.metric_literals: List[Tuple[int, str, str]] = []  # line, name, qual
        self.bail_literals: List[Tuple[int, str, str]] = []  # line, reason, qual
        self.functions: List[FuncInfo] = []
        self.thread_entries: List[ThreadEntry] = []

        self._scope: List[str] = []      # qualname stack (defs + classes)
        self._func_stack: List[FuncInfo] = []
        self.visit(tree)

    # -- scope bookkeeping ---------------------------------------------------
    def _qual(self) -> str:
        return ".".join(self._scope)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def _visit_func(self, node) -> None:
        self._scope.append(node.name)
        fi = FuncInfo(self.path, self._qual(), node.name, node.lineno)
        for dec in node.decorator_list:
            dn = dec.func if isinstance(dec, ast.Call) else dec
            name = dn.attr if isinstance(dn, ast.Attribute) else (
                dn.id if isinstance(dn, ast.Name) else None)
            if name == "main_thread_only":
                fi.marked = True
        self.functions.append(fi)
        self._func_stack.append(fi)
        self.generic_visit(node)
        self._func_stack.pop()
        self._scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- imports -------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            root = a.name.split(".")[0]
            if root in ("time", "datetime", "random", "os"):
                self.module_alias[a.asname or root] = root

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module in ("time", "datetime", "random", "os"):
            for a in node.names:
                self.from_bind[a.asname or a.name] = (node.module, a.name)
        else:
            # any other from-import: a bare call of this name may target
            # a def in another package module (T1 resolution)
            for a in node.names:
                self.imported_names.add(a.asname or a.name)

    # -- expression-level facts ----------------------------------------------
    def _root_module(self, node) -> Optional[str]:
        """Canonical module of an attribute chain's root Name, walking
        through `datetime.datetime.now` style nesting."""
        while isinstance(node, ast.Attribute):
            node = node.value
        if isinstance(node, ast.Name):
            return self.module_alias.get(node.id)
        return None

    def visit_Attribute(self, node: ast.Attribute) -> None:
        mod = self._root_module(node.value)
        if mod == "time" and node.attr in _TIME_READS:
            self.clock_uses.append(
                (node.lineno, "time.%s" % node.attr, self._qual()))
        elif mod == "datetime" and node.attr in _DATETIME_READS:
            self.clock_uses.append(
                (node.lineno, "datetime.%s" % node.attr, self._qual()))
        elif mod == "random" and node.attr not in _RANDOM_OK:
            self.random_uses.append(
                (node.lineno, "random.%s" % node.attr, self._qual()))
        elif mod == "os" and node.attr == "urandom":
            self.random_uses.append(
                (node.lineno, "os.urandom", self._qual()))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            bind = self.from_bind.get(node.id)
            if bind is not None:
                mod, orig = bind
                if mod == "time" and orig in _TIME_READS:
                    self.clock_uses.append(
                        (node.lineno, "time.%s" % orig, self._qual()))
                elif mod == "datetime" and orig in ("datetime", "date"):
                    pass  # class reference; .now/.today caught via Attribute
                elif mod == "random" and orig not in _RANDOM_OK:
                    self.random_uses.append(
                        (node.lineno, "random.%s" % orig, self._qual()))
                elif mod == "os" and orig == "urandom":
                    self.random_uses.append(
                        (node.lineno, "os.urandom", self._qual()))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else None
        name = fn.id if isinstance(fn, ast.Name) else None
        callee = attr or name

        # argless random.Random() / Random() from-import = unseeded
        if callee == "Random" and not node.args and not node.keywords:
            mod = self._root_module(fn.value) if attr else \
                self.from_bind.get(name, (None,))[0]
            if mod == "random":
                self.random_uses.append(
                    (node.lineno, "random.Random()", self._qual()))

        # datetime.datetime.now() handled by visit_Attribute; from-import
        # `datetime` class: datetime.now() is Attribute(value=Name) where
        # Name binds ("datetime","datetime")
        if attr in _DATETIME_READS and isinstance(fn.value, ast.Name):
            bind = self.from_bind.get(fn.value.id)
            if bind is not None and bind[0] == "datetime":
                self.clock_uses.append(
                    (node.lineno, "%s.%s" % (bind[1], attr), self._qual()))

        # fault-site literals
        if callee in _FAULT_CALLS and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                self.fault_literals.append(
                    (node.lineno, a.value, self._qual()))
        elif callee == "check_faults" and len(node.args) >= 2:
            a = node.args[1]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                self.fault_literals.append(
                    (node.lineno, a.value, self._qual()))
        elif callee == "_fire" and node.args:
            # ChaosTransport._fire composes site_prefix + "." + site;
            # the default (and only) prefix is "overlay"
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                self.fault_literals.append(
                    (node.lineno, "overlay." + a.value, self._qual()))

        # native-bail classification literals (N4's Python side):
        # `_bail(stats, "reason")` gates in ledger/native_apply.py and
        # direct `record_bail("reason")` calls
        if callee == "_bail" and len(node.args) >= 2:
            a = node.args[1]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                self.bail_literals.append(
                    (node.lineno, a.value, self._qual()))
        elif callee == "record_bail" and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                self.bail_literals.append(
                    (node.lineno, a.value, self._qual()))

        # metric registrations
        if callee in _METRIC_CALLS and node.args:
            lit = _literal_prefix(node.args[0])
            if lit is not None:
                self.metric_literals.append(
                    (node.lineno, lit, self._qual()))
        # footprint-census registrations (ISSUE 19): a
        # `track_struct("<name>", ...)` enrollment surfaces the
        # per-struct gauge `footprint.struct.<name>` — cataloged like
        # any metric registration, so a bounded structure cannot join
        # the census undocumented
        elif callee == "track_struct" and node.args:
            lit = _literal_prefix(node.args[0])
            if lit is not None:
                self.metric_literals.append(
                    (node.lineno, "footprint.struct." + lit,
                     self._qual()))

        # thread entry points
        if callee == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    self._note_thread_entry(node.lineno, kw.value,
                                            "Thread(target=...)")
        elif callee == "submit" and node.args:
            self._note_thread_entry(node.lineno, node.args[0],
                                    "executor.submit(...)")
        elif callee == "spawn_worker":
            # util.threads.spawn_worker(name, target): the audited
            # worker factory — its target walks exactly like a bare
            # Thread(target=...) so routing a spawn through the registry
            # can never weaken the T1 discipline check
            if len(node.args) >= 2:
                self._note_thread_entry(node.lineno, node.args[1],
                                        "spawn_worker(...)")
            for kw in node.keywords:
                if kw.arg == "target":
                    self._note_thread_entry(node.lineno, kw.value,
                                            "spawn_worker(...)")

        # call-graph edge for the enclosing def
        if self._func_stack and callee is not None:
            self._func_stack[-1].calls.add((_call_kind(fn), callee))

        self.generic_visit(node)

    def _note_thread_entry(self, line: int, expr, via: str) -> None:
        if isinstance(expr, ast.Name):
            self.thread_entries.append(
                ThreadEntry(self.path, line, "bare", expr.id, None, via))
        elif isinstance(expr, ast.Attribute):
            self.thread_entries.append(
                ThreadEntry(self.path, line, _call_kind(expr), expr.attr,
                            None, via))
        elif isinstance(expr, ast.Lambda):
            calls: Set[Tuple[str, str]] = set()
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if isinstance(f, ast.Attribute):
                        calls.add((_call_kind(f), f.attr))
                    elif isinstance(f, ast.Name):
                        calls.add(("bare", f.id))
            self.thread_entries.append(
                ThreadEntry(self.path, line, "inline", None, calls, via))

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        names: List[str] = []
        t = node.type
        if t is None:
            names = ["<bare>"]
        elif isinstance(t, ast.Name):
            names = [t.id]
        elif isinstance(t, ast.Tuple):
            names = [e.id for e in t.elts if isinstance(e, ast.Name)]
        body_is_pass = all(
            isinstance(s, ast.Pass) or
            (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant)
             and s.value.value is Ellipsis)
            for s in node.body)
        if body_is_pass and any(
                n in ("<bare>", "Exception", "BaseException")
                for n in names):
            kind = names[0] if names else "<bare>"
            self.except_passes.append((node.lineno, kind, self._qual()))
        self.generic_visit(node)


def _call_kind(fn) -> str:
    """`bare` for f(), `self` for self.f(), `attr` for obj.f()."""
    if isinstance(fn, ast.Name):
        return "bare"
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "self":
        return "self"
    return "attr"


def _literal_prefix(node) -> Optional[str]:
    """Literal (or literal-prefix) of a metric-name expression:
    "a.b" -> "a.b"; "a.%s" % x -> "a.%s"; f"a.{x}" -> "a.%s"."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) and \
            isinstance(node.left, ast.Constant) and \
            isinstance(node.left.value, str):
        return node.left.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("%s")
        return "".join(parts)
    return None


# --------------------------------------------------------------------------
# Per-module rules


def rule_d1_wallclock(facts: ModuleFacts) -> List[Finding]:
    return [Finding("D1", facts.path, line, qual,
                    "wall-clock read `%s`: consensus/subsystem code must "
                    "take time from the injected VirtualClock (or "
                    "util.timer.real_* for sanctioned real-time "
                    "measurement)" % expr)
            for (line, expr, qual) in facts.clock_uses]


def rule_d2_randomness(facts: ModuleFacts) -> List[Finding]:
    return [Finding("D2", facts.path, line, qual,
                    "unseeded randomness `%s`: route through util.rnd "
                    "(seeded global stream) or a seeded random.Random; "
                    "os.urandom is for key generation only" % expr)
            for (line, expr, qual) in facts.random_uses]


def rule_e1_swallow(facts: ModuleFacts, e1_dirs: Sequence[str],
                    package_name: str) -> List[Finding]:
    parts = facts.path.split("/")
    try:
        sub = parts[parts.index(package_name) + 1]
    except (ValueError, IndexError):
        sub = parts[0] if len(parts) > 1 else ""
    if sub not in e1_dirs:
        return []
    return [Finding("E1", facts.path, line, qual,
                    "`except %s: pass` silently swallows in consensus "
                    "code — log it, count it, or narrow the type"
                    % kind)
            for (line, kind, qual) in facts.except_passes]


# --------------------------------------------------------------------------
# Whole-tree rules


def rule_f1_fault_sites(all_facts: Sequence[ModuleFacts],
                        registry: Set[str], registry_path: str,
                        docs_text: str, docs_name: str) -> List[Finding]:
    out: List[Finding] = []
    used: Set[str] = set()
    for facts in all_facts:
        for (line, site, qual) in facts.fault_literals:
            used.add(site)
            if site not in registry:
                out.append(Finding(
                    "F1", facts.path, line, qual,
                    "fault site %r is not in util.faults.KNOWN_SITES — "
                    "register it (and catalog it in %s)"
                    % (site, docs_name)))
    for site in sorted(registry):
        if site not in docs_text:
            out.append(Finding(
                "F1", registry_path, 1, "KNOWN_SITES",
                "registered fault site %r is missing from the %s site "
                "catalog" % (site, docs_name)))
        if site not in used:
            out.append(Finding(
                "F1", registry_path, 1, "KNOWN_SITES",
                "registered fault site %r has no should_fire/fire_point/"
                "check_faults call site left in the tree — remove it from "
                "the registry and %s" % (site, docs_name)))
    return out


def rule_m1_metric_catalog(all_facts: Sequence[ModuleFacts],
                           docs_text: str, docs_name: str) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[str] = set()
    for facts in all_facts:
        for (line, name, qual) in facts.metric_literals:
            probe = name.split("%")[0]
            if name in seen:
                continue
            seen.add(name)
            if probe not in docs_text:
                out.append(Finding(
                    "M1", facts.path, line, qual,
                    "metric %r is registered in code but absent from %s "
                    "— add it to the catalog table" % (name, docs_name)))
    return out


_A1_ROW_TOKEN = re.compile(r"`([a-zA-Z][\w-]*)")


def rule_a1_admin_endpoints(all_facts: Sequence[ModuleFacts],
                            handler_path: str, docs_text: str,
                            docs_name: str) -> List[Finding]:
    """A1: every `cmd_*` handler in main/command_handler.py has a row in
    the docs/admin.md endpoint table, and every endpoint the table names
    still has a handler — the operator surface and its documentation
    move together (M1's pattern applied to the admin API).

    Endpoint names come from the first cell of each table row: every
    backtick-opened token's leading word (`bans[?action=...]` -> `bans`;
    combined rows like `` `setcursor`, `getcursor` `` yield each)."""
    handlers: Dict[str, Tuple[str, int]] = {}
    for facts in all_facts:
        if facts.path != handler_path:
            continue
        for fi in facts.functions:
            if fi.name.startswith("cmd_") and len(fi.name) > 4:
                handlers[fi.name[4:].replace("_", "-")] = \
                    (facts.path, fi.line)
    doc_rows: Dict[str, int] = {}
    in_table = False
    for lineno, line in enumerate(docs_text.splitlines(), 1):
        s = line.strip()
        if not s.startswith("|"):
            in_table = False
            continue
        cells = s.split("|")
        first = cells[1] if len(cells) > 1 else ""
        if "Endpoint" in first:
            in_table = True
            continue
        if not in_table or set(first.strip()) <= {"-", " ", ":"}:
            continue
        for tok in _A1_ROW_TOKEN.findall(first):
            doc_rows.setdefault(tok, lineno)
    out: List[Finding] = []
    for name, (path, line) in sorted(handlers.items()):
        if name not in doc_rows:
            out.append(Finding(
                "A1", path, line, "cmd_" + name.replace("-", "_"),
                "admin endpoint `%s` has no row in the %s endpoint "
                "table — document it (purpose + params) in the same "
                "change" % (name, docs_name)))
    for name, lineno in sorted(doc_rows.items()):
        if name not in handlers:
            out.append(Finding(
                "A1", docs_name, lineno, "",
                "%s documents endpoint `%s` but main/command_handler.py "
                "has no cmd_%s handler — remove or fix the row"
                % (docs_name, name, name.replace("-", "_"))))
    return out


def rule_t1_thread_discipline(all_facts: Sequence[ModuleFacts],
                              max_depth: int = 12) -> List[Finding]:
    """Call-graph walk from every thread entry point; reaching a
    `@main_thread_only` def is a violation.

    Resolution is by name (Python has no static dispatch), with
    precision per call kind: bare `f()` and `self.f()` resolve within
    the caller's module first; `obj.f()` resolves package-wide unless
    the name is on the generic-method stoplist (_GENERIC_ATTRS). A call
    to a *marked* name triggers regardless of kind. The remaining
    over-approximation is the right bias for a discipline check — a
    false edge is an allowlist line with a justification, a missed edge
    is a silent determinism bug.
    """
    from collections import deque

    by_name: Dict[str, List[FuncInfo]] = {}
    by_mod_name: Dict[Tuple[str, str], List[FuncInfo]] = {}
    imports_of: Dict[str, Set[str]] = {}
    marked_names: Set[str] = set()
    for facts in all_facts:
        imports_of[facts.path] = facts.imported_names
        for fi in facts.functions:
            by_name.setdefault(fi.name, []).append(fi)
            by_mod_name.setdefault((fi.path, fi.name), []).append(fi)
            if fi.marked:
                marked_names.add(fi.name)
    if not marked_names:
        return []

    def resolve(caller_path: str, kind: str,
                name: str) -> List[FuncInfo]:
        local = by_mod_name.get((caller_path, name), [])
        if kind == "bare":
            # same module, else a from-imported name targets its defs
            # elsewhere in the package (stdlib imports just miss)
            if local or name not in imports_of.get(caller_path, ()):
                return local
            return by_name.get(name, [])
        if kind == "self" and local:
            return local
        if name.startswith("__") or name in _GENERIC_ATTRS:
            # cross-object generic names (sock.close, thread.start)
            # resolve to nothing; self-calls already matched above
            return []
        return by_name.get(name, [])

    def walk(entry: ThreadEntry) -> Optional[List[str]]:
        if entry.func_name is not None:
            seeds = [(entry.func_kind, entry.func_name)]
        else:
            seeds = sorted(entry.inline_calls)
        seen: Set[int] = set()
        frontier: deque = deque()
        for (kind, name) in seeds:
            if name in marked_names:
                return [name]
            for fi in resolve(entry.path, kind, name):
                frontier.append((fi, (name,)))
        while frontier:
            fi, chain = frontier.popleft()
            if id(fi) in seen or len(chain) > max_depth:
                continue
            seen.add(id(fi))
            for (kind, name) in sorted(fi.calls):
                if name in marked_names:
                    return list(chain) + [name]
                for cand in resolve(fi.path, kind, name):
                    if id(cand) not in seen:
                        frontier.append((cand, chain + (name,)))
        return None

    out: List[Finding] = []
    for facts in all_facts:
        for entry in facts.thread_entries:
            chain = walk(entry)
            if chain is not None:
                out.append(Finding(
                    "T1", entry.path, entry.line, "",
                    "worker entry point (%s) can reach "
                    "@main_thread_only function via %s — workers must "
                    "hand results to consensus with clock.post_to_main"
                    % (entry.via, " -> ".join(chain))))
    return out
