"""sctlint engine: file walking, allowlist, rule orchestration.

The allowlist file (`stellar_core_tpu/analysis/allowlist.txt`) is the
single place intentional exceptions live. One entry per line:

    RULE path[#qualname-prefix] -- justification

e.g.

    D1 stellar_core_tpu/util/timer.py -- the clock abstraction itself

An entry suppresses every finding of RULE in that file (optionally
narrowed to functions whose qualname starts with the given prefix). A
justification is mandatory; an entry that matches nothing is STALE and
reported as an error — the allowlist can only shrink or be re-justified,
never rot. `--` and the em-dash `—` are both accepted separators.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple


@dataclass
class Finding:
    rule: str
    path: str        # repo-relative, posix separators
    line: int
    qualname: str    # enclosing def/class scope, "" at module level
    message: str

    def format(self) -> str:
        where = "%s:%d" % (self.path, self.line)
        if self.qualname:
            where += " (%s)" % self.qualname
        return "%s %s: %s" % (self.rule, where, self.message)


@dataclass
class AllowEntry:
    rule: str
    path: str
    qual: str                 # "" = whole file
    justification: str
    lineno: int
    matched: int = 0

    def covers(self, f: Finding) -> bool:
        return (f.rule == self.rule and f.path == self.path and
                (not self.qual or f.qualname.startswith(self.qual)))


@dataclass
class LintConfig:
    repo_root: str
    package_dir: str                     # absolute path to the package
    package_name: str
    allowlist_path: Optional[str]
    docs_metrics_path: Optional[str]
    docs_robustness_path: Optional[str]
    fault_registry: Optional[Set[str]]   # None = skip F1
    fault_registry_path: str = ""
    e1_dirs: Tuple[str, ...] = ("scp", "herder", "ledger", "bucket")
    enabled_rules: Tuple[str, ...] = ("D1", "D2", "T1", "E1", "F1", "M1",
                                      "S1", "FL1", "B1",
                                      "N1", "N2", "N3", "N4", "A1")
    # -- dataflow rules (S1/FL1/B1, flowrules.py) --------------------------
    s1_dirs: Tuple[str, ...] = ("scp", "herder", "ledger", "bucket",
                                "crypto", "history")
    fl1_dirs: Tuple[str, ...] = ("scp", "herder", "ledger")
    b1_root_classes: Tuple[str, ...] = ("Application", "Herder",
                                        "OverlayManager", "LedgerManager")
    # per-file facts/results cache under build/sctlint-cache; None (the
    # fixture default) disables caching entirely
    cache_dir: Optional[str] = None
    # -- C-side (N1-N4) and admin-surface (A1) extensions ------------------
    native_dir: Optional[str] = None     # *.c scanned here; None = skip N*
    docs_observability_path: Optional[str] = None
    docs_admin_path: Optional[str] = None  # None = skip A1
    command_handler_path: str = ""       # repo-relative .py with cmd_*
    bail_test_path: Optional[str] = None  # test tied to the N4 taxonomy
    op_type_names: Optional[Dict[int, str]] = None  # None = skip op check


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)   # pre-allowlist
    violations: List[Finding] = field(default_factory=list)  # post-allowlist
    stale_entries: List[AllowEntry] = field(default_factory=list)
    parse_errors: List[str] = field(default_factory=list)
    cache_hits: int = 0
    cache_misses: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations and not self.stale_entries and \
            not self.parse_errors


_SEP_RE = re.compile(r"\s+(?:--|—)\s+")


def load_allowlist(path: str) -> List[AllowEntry]:
    entries: List[AllowEntry] = []
    with open(path, encoding="utf-8") as fh:
        for i, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = _SEP_RE.split(line, maxsplit=1)
            if len(parts) != 2 or not parts[1].strip():
                raise ValueError(
                    "%s:%d: allowlist entry needs a justification "
                    "('RULE path -- why'): %r" % (path, i, line))
            head, justification = parts[0].split(), parts[1].strip()
            if len(head) != 2:
                raise ValueError(
                    "%s:%d: expected 'RULE path[#qual]', got %r"
                    % (path, i, parts[0]))
            rule, target = head
            fpath, _, qual = target.partition("#")
            entries.append(AllowEntry(rule, fpath, qual, justification, i))
    return entries


def _read(path: Optional[str]) -> str:
    if path is None or not os.path.exists(path):
        return ""
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def default_config(repo_root: Optional[str] = None) -> LintConfig:
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    pkg = os.path.join(repo_root, "stellar_core_tpu")
    docs = os.path.join(repo_root, "docs")
    # no fallback: if the registry import breaks, the lint run must die
    # loudly rather than silently dropping the F1 rule and printing
    # "clean" (fault_registry=None is only for fixture configs that
    # explicitly opt out of F1)
    from ..util.faults import KNOWN_SITES
    registry: Optional[Set[str]] = set(KNOWN_SITES)
    # same no-fallback stance as KNOWN_SITES: if the op-name table
    # import breaks, the lint run dies loudly rather than silently
    # skipping N4's op-type leg
    from ..ledger.apply_stats import OP_TYPE_NAMES
    cfg = LintConfig(
        repo_root=repo_root,
        package_dir=pkg,
        package_name="stellar_core_tpu",
        allowlist_path=os.path.join(pkg, "analysis", "allowlist.txt"),
        docs_metrics_path=os.path.join(docs, "metrics.md"),
        docs_robustness_path=os.path.join(docs, "robustness.md"),
        fault_registry=registry,
        fault_registry_path="stellar_core_tpu/util/faults.py",
        native_dir=os.path.join(pkg, "native"),
        docs_observability_path=os.path.join(docs, "observability.md"),
        docs_admin_path=os.path.join(docs, "admin.md"),
        command_handler_path="stellar_core_tpu/main/command_handler.py",
        bail_test_path=os.path.join(repo_root, "tests",
                                    "test_apply_cockpit.py"),
        op_type_names=dict(OP_TYPE_NAMES),
        cache_dir=os.path.join(repo_root, "build", "sctlint-cache"),
    )
    _apply_pyproject(cfg)
    return cfg


def _apply_pyproject(cfg: LintConfig) -> None:
    """Honor a `[tool.sctlint]` stanza in pyproject.toml (shared config
    home with `[tool.ruff]`, so one file drives both linters).

    Deliberately NOT tomllib: the stanza is defined as flat single-line
    `key = "value"` / `key = ["a", "b"]` entries and is parsed with the
    same simple scanner on every interpreter, so behavior can never
    diverge between py3.10 (no tomllib) and 3.11+. Anything the scanner
    can't read — multi-line arrays, nested tables — yields nothing for
    that key and the default stays: misparses FAIL SAFE to the full
    rule set, never to a weaker gate."""
    pp = os.path.join(cfg.repo_root, "pyproject.toml")
    if not os.path.exists(pp):
        return
    data: Dict[str, object] = {}
    in_stanza = False
    for line in _read(pp).splitlines():
        s = line.split("#", 1)[0].strip()
        if s.startswith("["):
            in_stanza = s == "[tool.sctlint]"
            continue
        if in_stanza and "=" in s:
            k, _, v = s.partition("=")
            v = v.strip()
            if v.startswith("[") and v.endswith("]"):
                data[k.strip()] = [x.strip().strip("\"'")
                                  for x in v.strip("[]").split(",")
                                  if x.strip()]
            elif not v.startswith("["):
                data[k.strip()] = v.strip("\"'")
    if data.get("allowlist"):
        cfg.allowlist_path = os.path.join(cfg.repo_root,
                                          str(data["allowlist"]))
    # empty lists count as "not set": an empty rules list would make
    # the whole gate vacuously green
    if isinstance(data.get("rules"), list) and data["rules"]:
        cfg.enabled_rules = tuple(str(r) for r in data["rules"])
    if isinstance(data.get("e1-dirs"), list) and data["e1-dirs"]:
        cfg.e1_dirs = tuple(str(d) for d in data["e1-dirs"])
    if isinstance(data.get("s1-dirs"), list) and data["s1-dirs"]:
        cfg.s1_dirs = tuple(str(d) for d in data["s1-dirs"])
    if isinstance(data.get("fl1-dirs"), list) and data["fl1-dirs"]:
        cfg.fl1_dirs = tuple(str(d) for d in data["fl1-dirs"])


def _py_files(package_dir: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def _c_files(native_dir: Optional[str]) -> List[str]:
    if native_dir is None or not os.path.isdir(native_dir):
        return []
    out = []
    for dirpath, dirnames, filenames in os.walk(native_dir):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build")]
        for fn in sorted(filenames):
            if fn.endswith(".c"):
                out.append(os.path.join(dirpath, fn))
    return out


def _config_digest(cfg: LintConfig) -> str:
    """Every knob that can change a PER-FILE verdict, folded into the
    cache key (tree-wide rules re-run every time anyway)."""
    import hashlib
    blob = repr((cfg.enabled_rules, cfg.e1_dirs, cfg.s1_dirs,
                 cfg.fl1_dirs, cfg.package_name))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def _per_module_findings(cfg: LintConfig, facts, flow) -> List[Finding]:
    """The per-module (cacheable) Python rules for one file."""
    from . import flowrules as FR
    from . import rules as R
    out: List[Finding] = []
    if "D1" in cfg.enabled_rules:
        out.extend(R.rule_d1_wallclock(facts))
    if "D2" in cfg.enabled_rules:
        out.extend(R.rule_d2_randomness(facts))
    if "E1" in cfg.enabled_rules:
        out.extend(R.rule_e1_swallow(facts, cfg.e1_dirs,
                                     cfg.package_name))
    if "S1" in cfg.enabled_rules:
        out.extend(FR.rule_s1_set_order(flow, cfg.s1_dirs,
                                        cfg.package_name))
    if "FL1" in cfg.enabled_rules:
        out.extend(FR.rule_fl1_float(flow, cfg.fl1_dirs,
                                     cfg.package_name))
    return out


def run_analysis(config: Optional[LintConfig] = None,
                 files: Optional[Sequence[str]] = None) -> AnalysisResult:
    """Run every enabled rule. `files` (absolute or repo-relative)
    restricts the per-module rules (D1/D2/E1/S1/FL1 for .py, N1/N2/N3
    for .c) to those files — the `--changed` fast path; tree-wide rules
    (T1/F1/M1/B1/N4/A1) always scan the whole package, since their
    facts are cross-module (and cross-language). Per-file parsing and
    per-module findings are served from the content-addressed cache
    (cache.py) when `cfg.cache_dir` is set."""
    from . import crules as C
    from . import flowrules as FR
    from . import rules as R
    from .cache import SctlintCache

    cfg = config or default_config()
    res = AnalysisResult()
    cache = SctlintCache(cfg.cache_dir, _config_digest(cfg))

    all_paths = _py_files(cfg.package_dir)
    facts_by_path: Dict[str, "R.ModuleFacts"] = {}
    flow_by_path: Dict[str, "FR.FlowFacts"] = {}
    findings_by_path: Dict[str, List[Finding]] = {}
    for abspath in all_paths:
        rel = os.path.relpath(abspath, cfg.repo_root).replace(os.sep, "/")
        with open(abspath, "rb") as fh:
            data = fh.read()
        key = cache.key_for(rel, data)
        entry = cache.get(key)
        if entry is None:
            try:
                tree = ast.parse(data.decode("utf-8"), filename=rel)
            except (SyntaxError, UnicodeDecodeError) as e:
                res.parse_errors.append("%s: %s" % (rel, e))
                continue
            facts = R.ModuleFacts(rel, tree)
            flow = FR.FlowFacts(rel, tree)
            perfile = _per_module_findings(cfg, facts, flow)
            cache.put(key, (facts, flow, perfile))
        else:
            facts, flow, perfile = entry
        facts_by_path[rel] = facts
        flow_by_path[rel] = flow
        findings_by_path[rel] = perfile

    n_rules_on = any(r in cfg.enabled_rules
                     for r in ("N1", "N2", "N3", "N4"))
    cfacts_by_path: Dict[str, "C.CFileFacts"] = {}
    if n_rules_on:
        for abspath in _c_files(cfg.native_dir):
            rel = os.path.relpath(abspath, cfg.repo_root) \
                .replace(os.sep, "/")
            with open(abspath, "rb") as fh:
                data = fh.read()
            key = cache.key_for(rel, data)
            entry = cache.get(key)
            if entry is None:
                try:
                    cfacts = C.CFileFacts(rel, data.decode("utf-8"))
                except ValueError as e:
                    res.parse_errors.append("%s: %s" % (rel, e))
                    continue
                cper: List[Finding] = []
                if "N1" in cfg.enabled_rules:
                    cper.extend(C.rule_n1_nogil_python(cfacts))
                if "N2" in cfg.enabled_rules:
                    cper.extend(C.rule_n2_alloc_discipline(cfacts))
                if "N3" in cfg.enabled_rules:
                    cper.extend(C.rule_n3_lock_balance(cfacts))
                cache.put(key, (cfacts, cper))
            else:
                cfacts, cper = entry
            cfacts_by_path[rel] = cfacts
            findings_by_path[rel] = cper

    restrict: Optional[Set[str]] = None
    if files is not None:
        restrict = set()
        for f in files:
            a = f if os.path.isabs(f) else os.path.join(cfg.repo_root, f)
            restrict.add(os.path.relpath(a, cfg.repo_root)
                         .replace(os.sep, "/"))

    all_facts = list(facts_by_path.values())
    all_flow = list(flow_by_path.values())
    for rel in sorted(findings_by_path):
        if restrict is not None and rel not in restrict:
            continue
        res.findings.extend(findings_by_path[rel])

    if "B1" in cfg.enabled_rules:
        res.findings.extend(FR.rule_b1_bounded_structs(
            all_flow, cfg.b1_root_classes,
            "%s/util/footprint.py" % cfg.package_name))
    if "T1" in cfg.enabled_rules:
        res.findings.extend(R.rule_t1_thread_discipline(all_facts))
    if "F1" in cfg.enabled_rules and cfg.fault_registry is not None:
        res.findings.extend(R.rule_f1_fault_sites(
            all_facts, set(cfg.fault_registry), cfg.fault_registry_path,
            _read(cfg.docs_robustness_path), "docs/robustness.md"))
    if "M1" in cfg.enabled_rules:
        res.findings.extend(R.rule_m1_metric_catalog(
            all_facts, _read(cfg.docs_metrics_path), "docs/metrics.md"))
    if "N4" in cfg.enabled_rules and cfacts_by_path:
        py_bails = [(facts.path, line, reason, qual)
                    for facts in all_facts
                    for (line, reason, qual) in facts.bail_literals]
        res.findings.extend(C.rule_n4_cross_boundary(
            [cfacts_by_path[k] for k in sorted(cfacts_by_path)],
            py_bails,
            _read(cfg.docs_observability_path), "docs/observability.md",
            _read(cfg.docs_metrics_path), "docs/metrics.md",
            _read(cfg.bail_test_path) if cfg.bail_test_path else None,
            "tests/test_apply_cockpit.py",
            cfg.op_type_names))
    if "A1" in cfg.enabled_rules and cfg.docs_admin_path:
        # a MISSING admin doc reads as "" and flags every handler —
        # fail-safe, same stance as M1's missing metrics catalog
        # (docs_admin_path=None is the explicit fixture opt-out)
        res.findings.extend(R.rule_a1_admin_endpoints(
            all_facts, cfg.command_handler_path,
            _read(cfg.docs_admin_path), "docs/admin.md"))

    entries: List[AllowEntry] = []
    if cfg.allowlist_path and os.path.exists(cfg.allowlist_path):
        entries = load_allowlist(cfg.allowlist_path)

    for f in res.findings:
        covered = False
        for e in entries:
            if e.covers(f):
                e.matched += 1
                covered = True
        if not covered:
            res.violations.append(f)

    # stale entries only meaningful on full-tree runs with their rule
    # enabled: a --changed run that skipped a file (or an M1-only run)
    # must not flag unrelated entries as stale
    if restrict is None:
        res.stale_entries = [e for e in entries
                             if e.matched == 0 and
                             e.rule in cfg.enabled_rules]
    res.cache_hits = cache.hits
    res.cache_misses = cache.misses
    cache.prune()
    return res
