"""Dataflow rules for sctlint: S1 (set-iteration determinism), FL1
(float leakage into replicated state), B1 (bounded-memory census parity).

D1/D2 police the *inputs* a replicated transition function may read;
these rules police the two remaining silent-divergence classes a Python
core carries (the ACE-Runtime determinism contract, PAPERS.md
2603.10242) plus the unbounded-growth class the footprint census
(ISSUE 19) can only observe at runtime:

- **S1 — set-ordered iteration.** `set` iteration order depends on
  `PYTHONHASHSEED` for str/bytes elements, so any set-ordered sequence
  that feeds hashing, XDR serialization, message emission, or a
  returned collection in consensus-critical packages diverges
  bit-identically-replicated state across nodes. A lightweight
  intraprocedural dataflow pass (below) tracks set-origin values
  through assignments, comprehensions, `list()`/`tuple()`/`join`/`*`
  laundering and module-local helper returns; `sorted(...)` is the
  sanctioned neutralizer. The runtime twin is the PYTHONHASHSEED
  differential gate (tests/test_hashseed_differential.py): two
  subprocesses under different hash seeds must externalize identical
  per-height header hashes, bucket-list hashes and txset orderings.
- **FL1 — float leakage.** IEEE-754 arithmetic is deterministic per
  platform but its *use* in fee/balance/sequence math invites rounding
  drift the moment any operand path differs; replicated-state code in
  `ledger/`, `scp/`, `herder/` must stay on integers. Flagged: true
  division (`/` always yields float), arithmetic on float-origin
  operands, and float-typed returns. Telemetry/metrics call sites
  resolve via allowlist lines with per-site justifications.
- **B1 — bounded-memory parity.** Every long-lived subsystem class
  (discovered by walking Application/Herder/OverlayManager/
  LedgerManager construction, transitively) whose instance-attribute
  containers grow from runtime handlers must be bounded by
  construction (`deque(maxlen=...)`, `LRUCache`,
  `RandomEvictionCache`), carry explicit cap/eviction logic, or be
  enrolled in the footprint census via `track_struct(...)` — and every
  enrollment must still reference a live instance attribute
  (registry ⇄ code parity, the F1/M1/N4 shape applied to memory).

Like every sctlint rule the bias is over-approximation in the safe
direction: a false edge is an allowlist line with a justification, a
missed edge is a consensus fork or an OOM at height 10^6.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import Finding

# -- taint lattice ----------------------------------------------------------
# None        clean
# ("set",)    unordered set-valued (or a mapping keyed in set order):
#             safe to return/pass, hazardous to iterate unsorted
# ("seq", line, desc)
#             an ORDERED sequence derived from set iteration order; the
#             recorded site is where the nondeterministic ordering was
#             created (list(s), a comprehension, join, *-unpack, ...)
# ("float",)  float-typed value

_SET = ("set",)
_FLOAT = ("float",)

# callables whose result order/content is insensitive to input order
_ORDER_INSENSITIVE = {
    "len", "sum", "min", "max", "any", "all", "bool", "sorted",
    "frozenset", "abs", "int", "str", "repr", "id", "isinstance",
    "Counter",
}
# sequence-producing callables that PRESERVE the argument's iteration
# order (the laundering set: list(s) looks innocent, still hashes dirty)
_ORDER_PRESERVING = {"list", "tuple", "iter", "enumerate", "reversed"}
# set methods returning another set
_SET_METHODS = {"union", "intersection", "difference",
                "symmetric_difference", "copy"}
# sink callables: hashing, XDR serialization, message emission. Matched
# by exact name or substring ("hash"/"xdr"/"digest") on the called name.
_SINK_NAMES = {"sha256", "digest", "hexdigest", "broadcast_message",
               "send_message", "emit", "rebroadcast", "emit_envelope",
               "pack", "dumps"}
_SINK_SUBSTR = ("hash", "xdr", "digest")

_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv,
              ast.Mod, ast.Pow)

# -- B1 vocabulary ----------------------------------------------------------
_UNBOUNDED_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict"}
_BOUNDED_CTORS = {"LRUCache", "RandomEvictionCache"}
_GROWTH_METHODS = {"append", "appendleft", "add", "insert", "extend",
                   "update", "setdefault", "push"}
_EVICT_METHODS = {"pop", "popleft", "popitem", "clear", "discard",
                  "remove", "evict", "prune"}
# methods that run at wiring/teardown time, not from live handlers: a
# container only ever grown here is filled once, not leaked into
_SETUP_METHODS = {"__init__", "__post_init__", "start", "setup",
                  "configure", "enable", "arm", "wire", "rewire",
                  "shutdown", "stop", "restore", "load", "bootstrap"}


def _sink_call(name: Optional[str]) -> bool:
    if not name:
        return False
    if name in _SINK_NAMES:
        return True
    low = name.lower()
    return any(s in low for s in _SINK_SUBSTR)


def _callee_name(fn) -> Optional[str]:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _ann_is_set(ann) -> bool:
    """Annotation names a set type (Set[...], FrozenSet[...], set)."""
    if isinstance(ann, ast.Subscript):
        ann = ann.value
    if isinstance(ann, ast.Attribute):
        return ann.attr in ("Set", "FrozenSet", "MutableSet",
                            "AbstractSet")
    if isinstance(ann, ast.Name):
        return ann.id in ("set", "frozenset", "Set", "FrozenSet",
                          "MutableSet", "AbstractSet")
    return False


def _ann_is_float(ann) -> bool:
    if isinstance(ann, ast.Name):
        return ann.id == "float"
    return False


class FlowFn:
    """Summary of one function after the intraprocedural pass."""

    __slots__ = ("qualname", "name", "line", "returns_set",
                 "returns_float")

    def __init__(self, qualname: str, name: str, line: int) -> None:
        self.qualname = qualname
        self.name = name
        self.line = line
        self.returns_set = False
        self.returns_float = False


class ClassFlow:
    """B1 facts for one class definition."""

    __slots__ = ("name", "qualname", "line", "containers", "constructed",
                 "growths", "caps")

    def __init__(self, name: str, qualname: str, line: int) -> None:
        self.name = name
        self.qualname = qualname
        self.line = line
        # attr -> (kind, bounded, line)
        self.containers: Dict[str, Tuple[str, bool, int]] = {}
        # attr -> constructed class name (self.x = SomeClass(...))
        self.constructed: Dict[str, str] = {}
        # (method, attr, op, line) growth mutations outside __init__
        self.growths: List[Tuple[str, str, str, int]] = []
        # attrs with cap/eviction evidence anywhere in the class
        self.caps: Set[str] = set()


class FlowFacts:
    """Per-module dataflow facts: S1/FL1 candidate findings (computed at
    parse time so they cache with the module), per-function return
    summaries, and the class/enrollment facts the tree-wide B1 rule
    consumes. Holds no AST after construction — picklable for the
    content-sha cache."""

    def __init__(self, path: str, tree: ast.AST) -> None:
        self.path = path
        self.functions: Dict[str, FlowFn] = {}   # by bare name, last wins
        self.classes: List[ClassFlow] = []
        # (line, qual, literal-name, frozenset of referenced attr tails)
        self.track_calls: List[Tuple[int, str, str, frozenset]] = []
        self.module_names: Set[str] = set()      # module-level assigns
        self.self_attrs: Set[str] = set()        # every `self.X = ...`
        # the attribute universe B1's reverse-parity check resolves
        # against: function/class names, class-level constants,
        # module-level names — anything a track_struct lambda may
        # legitimately dereference
        self.defined_names: Set[str] = set()
        # candidate findings: (rule, line, qual, message)
        self.s1_sites: List[Tuple[int, str, str]] = []
        self.fl1_sites: List[Tuple[int, str, str]] = []

        self._fn_nodes: List[Tuple[ast.AST, str, Optional[ClassFlow]]] = []
        self._collect(tree)
        self._summarize()
        self._analyze()
        del self._fn_nodes               # drop AST references

    # -- structural collection ----------------------------------------------
    def _collect(self, tree: ast.AST) -> None:
        def walk(node, scope: List[str], cls: Optional[ClassFlow]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    cf = ClassFlow(child.name,
                                   ".".join(scope + [child.name]),
                                   child.lineno)
                    self.classes.append(cf)
                    self.defined_names.add(child.name)
                    walk(child, scope + [child.name], cf)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = ".".join(scope + [child.name])
                    self._fn_nodes.append((child, qual, cls))
                    self.defined_names.add(child.name)
                    if cls is not None:
                        self._class_facts(cls, child)
                    self._scan_track_and_attrs(child, qual)
                    walk(child, scope + [child.name], None)
                else:
                    if isinstance(child, (ast.Assign, ast.AnnAssign)):
                        targets = child.targets \
                            if isinstance(child, ast.Assign) \
                            else [child.target]
                        for t in targets:
                            if isinstance(t, ast.Name):
                                self.defined_names.add(t.id)
                                if not scope:
                                    self.module_names.add(t.id)
                    walk(child, scope, cls)
        walk(tree, [], None)

    def _scan_track_and_attrs(self, fnode, qual: str) -> None:
        """track_struct enrollments + the universe of self-attrs (the
        reverse-parity side of B1)."""
        for node in ast.walk(fnode):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self.self_attrs.add(t.attr)
            if isinstance(node, ast.Call) and \
                    _callee_name(node.func) == "track_struct" and node.args:
                a = node.args[0]
                if not (isinstance(a, ast.Constant) and
                        isinstance(a.value, str)):
                    continue
                refs: Set[str] = set()
                for arg in list(node.args[1:]) + \
                        [kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Attribute):
                            refs.add(sub.attr)
                        elif isinstance(sub, ast.Name):
                            refs.add(sub.id)
                self.track_calls.append(
                    (node.lineno, qual, a.value, frozenset(refs)))

    def _class_facts(self, cls: ClassFlow, fnode) -> None:
        """Container inits, constructions, growths and cap evidence for
        one method of `cls`."""
        meth = fnode.name
        in_init = meth == "__init__"
        for node in ast.walk(fnode):
            # self.X = <container or construction> (init only)
            if in_init and isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                val = node.value
                for t in targets:
                    if not (isinstance(t, ast.Attribute) and
                            isinstance(t.value, ast.Name) and
                            t.value.id == "self"):
                        continue
                    kind, bounded = self._container_of(val)
                    if kind is not None:
                        cls.containers[t.attr] = (kind, bounded,
                                                  node.lineno)
                    elif isinstance(val, ast.Call):
                        cn = _callee_name(val.func)
                        if cn and cn[:1].isupper():
                            cls.constructed[t.attr] = cn
            # growth / eviction on self.X
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                recv = node.func.value
                if isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name) and \
                        recv.value.id == "self":
                    if node.func.attr in _GROWTH_METHODS and not in_init:
                        cls.growths.append((meth, recv.attr,
                                            node.func.attr, node.lineno))
                    elif node.func.attr in _EVICT_METHODS:
                        cls.caps.add(recv.attr)
            if isinstance(node, ast.Assign) and not in_init:
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Attribute) and \
                            isinstance(t.value.value, ast.Name) and \
                            t.value.value.id == "self":
                        cls.growths.append((meth, t.value.attr, "[]=",
                                            node.lineno))
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Attribute) and \
                            isinstance(t.value.value, ast.Name) and \
                            t.value.value.id == "self":
                        cls.caps.add(t.value.attr)
            # len(self.X) inside a comparison or loop/branch test
            if isinstance(node, (ast.Compare, ast.While, ast.If)):
                tests = [node.test] if isinstance(node, (ast.While,
                                                         ast.If)) \
                    else [node]
                for test in tests:
                    for sub in ast.walk(test):
                        if isinstance(sub, ast.Call) and \
                                _callee_name(sub.func) == "len" and \
                                sub.args and \
                                isinstance(sub.args[0], ast.Attribute) \
                                and isinstance(sub.args[0].value,
                                               ast.Name) and \
                                sub.args[0].value.id == "self":
                            cls.caps.add(sub.args[0].attr)

    @staticmethod
    def _container_of(val) -> Tuple[Optional[str], bool]:
        """(kind, bounded) when `val` constructs a container literal."""
        if isinstance(val, ast.Dict):
            return "dict", False
        if isinstance(val, ast.List):
            return "list", False
        if isinstance(val, ast.Set):
            return "set", False
        if isinstance(val, ast.Call):
            cn = _callee_name(val.func)
            if cn == "deque":
                bounded = any(kw.arg == "maxlen" for kw in val.keywords) \
                    or len(val.args) >= 2
                return "deque", bounded
            if cn in _UNBOUNDED_CTORS:
                return cn, False
            if cn in _BOUNDED_CTORS:
                return cn, True
        return None, False

    # -- function summaries (the module-local helper hop) --------------------
    def _summarize(self) -> None:
        for fnode, qual, _cls in self._fn_nodes:
            self.functions[fnode.name] = FlowFn(qual, fnode.name,
                                                fnode.lineno)
        # fixpoint: a helper returning set()/a float taints its callers'
        # summaries; the tree is small, convergence takes 2-3 rounds
        for _ in range(4):
            changed = False
            for fnode, qual, cls in self._fn_nodes:
                fn = self.functions[fnode.name]
                pass_ = _FnPass(self, cls, collect=False)
                rs, rf = pass_.run(fnode)
                if rs and not fn.returns_set:
                    fn.returns_set = changed = True
                if rf and not fn.returns_float:
                    fn.returns_float = changed = True
            if not changed:
                break

    def _analyze(self) -> None:
        for fnode, qual, cls in self._fn_nodes:
            pass_ = _FnPass(self, cls, collect=True, qual=qual)
            pass_.run(fnode)


class _FnPass:
    """One forward walk over a function body: evaluates expression
    taints against a local environment, records S1/FL1 candidate sites
    when `collect` is set, and reports whether the function returns
    set-origin / float-origin values."""

    def __init__(self, facts: FlowFacts, cls: Optional[ClassFlow],
                 collect: bool, qual: str = "") -> None:
        self.facts = facts
        self.cls = cls
        self.collect = collect
        self.qual = qual
        self.env: Dict[str, Optional[tuple]] = {}
        self.kinds: Dict[str, str] = {}       # name -> container kind
        self.returned_names: Set[str] = set()
        self.returns_set = False
        self.returns_float = False
        self._seen_sites: Set[Tuple[int, int, str]] = set()

    # -- driver --------------------------------------------------------------
    def run(self, fnode) -> Tuple[bool, bool]:
        for arg in list(fnode.args.args) + list(fnode.args.kwonlyargs):
            if arg.annotation is not None:
                if _ann_is_set(arg.annotation):
                    self.env[arg.arg] = _SET
                elif _ann_is_float(arg.annotation):
                    self.env[arg.arg] = _FLOAT
        # pre-pass: names returned anywhere (loop-accumulator sink)
        for node in ast.walk(fnode):
            if isinstance(node, ast.Return) and \
                    isinstance(node.value, ast.Name):
                self.returned_names.add(node.value.id)
        for stmt in fnode.body:
            self._stmt(stmt)
        return self.returns_set, self.returns_float

    def _site(self, line: int, col: int, rule: str, msg: str) -> None:
        if not self.collect:
            return
        key = (line, col, msg)
        if key in self._seen_sites:
            return
        self._seen_sites.add(key)
        if rule == "S1":
            self.facts.s1_sites.append((line, self.qual, msg))
        else:
            self.facts.fl1_sites.append((line, self.qual, msg))

    # -- statements ----------------------------------------------------------
    def _stmt(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                         # nested defs analyzed separately
        if isinstance(node, ast.Assign):
            o = self._ev(node.value)
            k = self._kind_of(node.value)
            for t in node.targets:
                self._bind(t, o, k)
        elif isinstance(node, ast.AnnAssign):
            o = self._ev(node.value) if node.value is not None else None
            if node.value is None or o is None:
                if _ann_is_set(node.annotation):
                    o = _SET
                elif _ann_is_float(node.annotation):
                    o = _FLOAT
            self._bind(node.target, o, self._kind_of(node.value))
        elif isinstance(node, ast.AugAssign):
            o = self._ev(node.value)
            if isinstance(node.target, ast.Name):
                cur = self.env.get(node.target.id)
                new = self._binop_taint(node.op, cur, o, node)
                if new is not None:
                    self.env[node.target.id] = new
        elif isinstance(node, ast.Return):
            o = self._ev(node.value) if node.value is not None else None
            if o is not None:
                if o[0] == "set":
                    self.returns_set = True
                elif o[0] == "float":
                    self.returns_float = True
                    self._site(node.lineno, node.col_offset, "FL1",
                               "float-typed return: replicated-state "
                               "code must stay on integers (scale to "
                               "stroops/ppm)")
                elif o[0] == "seq":
                    self._site(o[1], 0, "S1",
                               "set-ordered sequence (%s) is returned — "
                               "wrap the set in sorted(...) at the "
                               "ordering point" % o[2])
        elif isinstance(node, ast.For):
            self._for(node)
        elif isinstance(node, (ast.While, ast.If)):
            self._ev(node.test)
            for s in node.body:
                self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._ev(item.context_expr)
            for s in node.body:
                self._stmt(s)
        elif isinstance(node, ast.Try):
            for s in node.body:
                self._stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in node.orelse + node.finalbody:
                self._stmt(s)
        elif isinstance(node, ast.Expr):
            self._ev(node.value)
        elif isinstance(node, (ast.Raise, ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._ev(child)

    def _bind(self, target, o, kind: Optional[str]) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = o
            if kind is not None:
                self.kinds[target.id] = kind
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            self.env[("self", target.attr)] = o
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, None, None)

    def _kind_of(self, val) -> Optional[str]:
        if val is None:
            return None
        k, _ = FlowFacts._container_of(val)
        return k

    def _for(self, node: ast.For) -> None:
        it = self._ev(node.iter)
        if it is not None and it[0] in ("set", "seq"):
            line = node.iter.lineno if it[0] == "set" else it[1]
            desc = "set" if it[0] == "set" else it[2]
            consumed = self._loop_consumes(node.body)
            if consumed:
                self._site(line, node.col_offset, "S1",
                           "iteration over %s in a loop that %s — wrap "
                           "the iterable in sorted(...)" % (desc,
                                                            consumed))
        self._bind(node.target, None, None)
        for s in node.body:
            self._stmt(s)
        for s in node.orelse:
            self._stmt(s)

    def _loop_consumes(self, body) -> Optional[str]:
        """Does this loop body leak iteration ORDER into consensus-
        visible state? (hash/XDR/emit calls, yields, or appends into a
        returned ordered accumulator; adds into sets/dicts are
        order-insensitive and stay clean)."""
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    name = _callee_name(node.func)
                    if _sink_call(name):
                        return "feeds `%s(...)`" % name
                    if name in ("append", "extend", "insert") and \
                            isinstance(node.func, ast.Attribute) and \
                            isinstance(node.func.value, ast.Name):
                        acc = node.func.value.id
                        if acc in self.returned_names and \
                                self.kinds.get(acc) != "set" and \
                                self.kinds.get(acc) != "dict":
                            return "builds returned collection `%s`" \
                                % acc
                elif isinstance(node, (ast.Yield, ast.YieldFrom)):
                    return "yields in iteration order"
        return None

    # -- expressions ---------------------------------------------------------
    def _ev(self, node) -> Optional[tuple]:
        if node is None:
            return None
        m = getattr(self, "_ev_%s" % type(node).__name__, None)
        if m is not None:
            return m(node)
        # default: evaluate children for side-record (sites), no taint
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._ev(child)
        return None

    def _ev_Name(self, node):
        return self.env.get(node.id)

    def _ev_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            o = self.env.get(("self", node.attr))
            if o is not None:
                return o
            if self.cls is not None:
                info = self.cls.containers.get(node.attr)
                if info is not None and info[0] == "set":
                    return _SET
            return None
        self._ev(node.value)
        return None

    def _ev_Constant(self, node):
        if isinstance(node.value, float):
            return _FLOAT
        return None

    def _ev_Set(self, node):
        for e in node.elts:
            self._ev(e)
        return _SET

    def _ev_SetComp(self, node):
        self._comp_generators(node)
        return _SET

    def _ev_DictComp(self, node):
        # a dict comprehension over a set inherits set insertion order
        if self._comp_generators(node):
            return _SET
        return None

    def _ev_ListComp(self, node):
        return self._ordered_comp(node, "list comprehension")

    def _ev_GeneratorExp(self, node):
        return self._ordered_comp(node, "generator expression")

    def _ordered_comp(self, node, what: str):
        tainted = self._comp_generators(node)
        self._ev(node.elt)
        if tainted:
            return ("seq", node.lineno, "%s over a set" % what)
        return None

    def _comp_generators(self, node) -> bool:
        tainted = False
        for gen in node.generators:
            o = self._ev(gen.iter)
            if o is not None and o[0] in ("set", "seq"):
                tainted = True
            self._bind(gen.target, None, None)
            for cond in gen.ifs:
                self._ev(cond)
        if isinstance(node, ast.DictComp):
            self._ev(node.key)
            self._ev(node.value)
        elif not isinstance(node, ast.SetComp):
            pass  # elt evaluated by caller where needed
        return tainted

    def _ev_List(self, node):
        return self._display(node)

    def _ev_Tuple(self, node):
        return self._display(node)

    def _display(self, node):
        out = None
        for e in node.elts:
            if isinstance(e, ast.Starred):
                o = self._ev(e.value)
                if o is not None and o[0] in ("set", "seq"):
                    out = ("seq", e.lineno, "*-unpack of a set")
            else:
                # a set-ordered sequence nested in a display keeps its
                # taint: `return list(s), x` leaks order just the same
                o = self._ev(e)
                if out is None and o is not None and o[0] == "seq":
                    out = o
        return out

    def _ev_BinOp(self, node):
        left = self._ev(node.left)
        right = self._ev(node.right)
        return self._binop_taint(node.op, left, right, node)

    def _binop_taint(self, op, left, right, node):
        sets = [o for o in (left, right) if o is not None and
                o[0] == "set"]
        if isinstance(op, (ast.BitOr, ast.BitAnd, ast.BitXor)) and sets:
            return _SET
        if isinstance(op, ast.Sub) and sets:
            return _SET
        if isinstance(op, ast.Div):
            self._site(node.lineno, node.col_offset, "FL1",
                       "true division always yields float — use // "
                       "(or integer ppm/stroop scaling) in "
                       "replicated-state code")
            return _FLOAT
        if isinstance(op, _ARITH_OPS):
            if any(o is not None and o[0] == "float"
                   for o in (left, right)):
                self._site(node.lineno, node.col_offset, "FL1",
                           "arithmetic on a float-origin operand in "
                           "replicated-state code")
                return _FLOAT
        return None

    def _ev_UnaryOp(self, node):
        return self._ev(node.operand)

    def _ev_IfExp(self, node):
        self._ev(node.test)
        a = self._ev(node.body)
        b = self._ev(node.orelse)
        return a or b

    def _ev_NamedExpr(self, node):
        o = self._ev(node.value)
        self._bind(node.target, o, self._kind_of(node.value))
        return o

    def _ev_Await(self, node):
        return self._ev(node.value)

    def _ev_Starred(self, node):
        return self._ev(node.value)

    def _ev_Subscript(self, node):
        self._ev(node.value)
        self._ev(node.slice)
        return None

    def _ev_Compare(self, node):
        self._ev(node.left)
        for c in node.comparators:
            self._ev(c)
        return None

    def _ev_BoolOp(self, node):
        out = None
        for v in node.values:
            o = self._ev(v)
            out = out or o
        return out

    def _ev_JoinedStr(self, node):
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                self._ev(v.value)
        return None

    def _ev_Lambda(self, node):
        return None                      # bodies run elsewhere

    def _ev_Call(self, node):
        name = _callee_name(node.func)
        argo = [self._ev(a) for a in node.args]
        for kw in node.keywords:
            self._ev(kw.value)

        # sinks first: a set-ordered value handed to hash/XDR/emit
        if _sink_call(name):
            for a, o in zip(node.args, argo):
                if o is not None and o[0] in ("set", "seq"):
                    where = o[1] if o[0] == "seq" else a.lineno
                    self._site(where, node.col_offset, "S1",
                               "set-ordered value feeds `%s(...)` — "
                               "sort it first" % name)
        # *-unpack of a set straight into any call's positional args
        # (sink callees already reported it through the arg check above)
        for a in node.args:
            if isinstance(a, ast.Starred) and not _sink_call(name):
                o = self._ev(a.value)
                if o is not None and o[0] in ("set", "seq"):
                    self._site(a.lineno, node.col_offset, "S1",
                               "*-unpack of a set into `%s(...)` — "
                               "sort it first" % (name or "call"))

        if name in ("set", "frozenset"):
            return _SET
        if name == "sorted" or name in _ORDER_INSENSITIVE:
            return None
        if name == "float":
            return _FLOAT
        if name in _ORDER_PRESERVING:
            if argo and argo[0] is not None and argo[0][0] == "set":
                return ("seq", node.lineno, "%s() of a set" % name)
            if argo and argo[0] is not None and argo[0][0] == "seq":
                return argo[0]
            return None
        if name == "fromkeys" and argo:
            if argo[0] is not None and argo[0][0] in ("set", "seq"):
                return _SET
            return None
        if isinstance(node.func, ast.Attribute):
            recv = self._ev(node.func.value)
            if recv is not None and recv[0] == "set":
                if name in _SET_METHODS:
                    return _SET
                if name in ("keys", "values", "items"):
                    return _SET
                if name == "pop":
                    return ("seq", node.lineno,
                            "set.pop() (arbitrary element)")
            if name == "join" and argo:
                o = argo[0]
                if o is not None and o[0] == "set":
                    return ("seq", node.lineno, "join() over a set")
                if o is not None and o[0] == "seq":
                    return o
        # module-local helper hop (bare f() or self.f())
        fn = self.facts.functions.get(name) if name else None
        if fn is not None and (isinstance(node.func, ast.Name) or
                               (isinstance(node.func, ast.Attribute) and
                                isinstance(node.func.value, ast.Name) and
                                node.func.value.id == "self")):
            if fn.returns_set:
                return _SET
            if fn.returns_float:
                return _FLOAT
        return None


# --------------------------------------------------------------------------
# Rules


def _subdir(path: str, package_name: str) -> str:
    parts = path.split("/")
    try:
        return parts[parts.index(package_name) + 1]
    except (ValueError, IndexError):
        return parts[0] if len(parts) > 1 else ""


def rule_s1_set_order(flow: FlowFacts, s1_dirs: Sequence[str],
                      package_name: str) -> List[Finding]:
    """S1: set-ordered iteration feeding hashing/serialization/emission/
    returned collections in consensus-critical packages."""
    if _subdir(flow.path, package_name) not in s1_dirs:
        return []
    return [Finding("S1", flow.path, line, qual, msg)
            for (line, qual, msg) in sorted(flow.s1_sites)]


def rule_fl1_float(flow: FlowFacts, fl1_dirs: Sequence[str],
                   package_name: str) -> List[Finding]:
    """FL1: float arithmetic / float-typed returns in replicated-state
    packages. Telemetry paths earn allowlist lines, not exemptions."""
    if _subdir(flow.path, package_name) not in fl1_dirs:
        return []
    return [Finding("FL1", flow.path, line, qual, msg)
            for (line, qual, msg) in sorted(flow.fl1_sites)]


def discover_longlived(all_flow: Sequence[FlowFacts],
                       roots: Sequence[str]) -> Dict[str, ClassFlow]:
    """Transitive closure of subsystem classes constructed (directly or
    through intermediates) during Application/Herder/OverlayManager/
    LedgerManager setup — name-resolved package-wide, the T1 stance."""
    by_name: Dict[str, ClassFlow] = {}
    for flow in all_flow:
        for cf in flow.classes:
            by_name.setdefault(cf.name, cf)
    out: Dict[str, ClassFlow] = {}
    frontier = [r for r in roots if r in by_name]
    while frontier:
        name = frontier.pop()
        if name in out:
            continue
        cf = by_name[name]
        out[name] = cf
        for ctor in cf.constructed.values():
            if ctor in by_name and ctor not in out:
                frontier.append(ctor)
    return out


def rule_b1_bounded_structs(all_flow: Sequence[FlowFacts],
                            roots: Sequence[str],
                            footprint_path: str) -> List[Finding]:
    """B1: long-lived subsystem containers grown from runtime handlers
    must be bounded by construction, carry cap/eviction logic, or be
    enrolled in the footprint census; and every `track_struct(...)`
    enrollment must still reference a live instance attribute."""
    path_of: Dict[str, str] = {}
    enrolled_attrs: Set[str] = set()
    known_attrs: Set[str] = set()
    track_calls: List[Tuple[str, int, str, str, frozenset]] = []
    for flow in all_flow:
        known_attrs |= flow.self_attrs | flow.defined_names
        for (line, qual, name, refs) in flow.track_calls:
            enrolled_attrs |= refs
            track_calls.append((flow.path, line, qual, name, refs))
        for cf in flow.classes:
            path_of.setdefault(cf.name, flow.path)

    longlived = discover_longlived(all_flow, roots)
    out: List[Finding] = []
    for name in sorted(longlived):
        cf = longlived[name]
        path = path_of.get(name, footprint_path)
        for attr in sorted(cf.containers):
            kind, bounded, line = cf.containers[attr]
            if bounded:
                continue
            growths = [(m, op, ln) for (m, a, op, ln) in cf.growths
                       if a == attr and m not in _SETUP_METHODS]
            if not growths:
                continue
            if attr in cf.caps:
                continue
            if attr in enrolled_attrs:
                continue
            m, op, _ln = growths[0]
            out.append(Finding(
                "B1", path, line, "%s.__init__" % cf.qualname,
                "unbounded %s `self.%s` on long-lived %s grows in "
                "handler `%s` (%s) with no cap/eviction — bound it "
                "(deque maxlen / LRUCache / explicit cap) or enroll it "
                "in the footprint census via track_struct(...)"
                % (kind, attr, name, m, op)))
    for (path, line, qual, name, refs) in sorted(track_calls):
        if not (refs & known_attrs):
            out.append(Finding(
                "B1", path, line, qual,
                "track_struct enrollment %r references no live "
                "attribute — the enrolled structure was removed or "
                "renamed; fix or drop the enrollment" % name))
    return out
