"""sctlint CLI: `python -m stellar_core_tpu.analysis [options] [files...]`
(or via the `tools/sctlint` wrapper, which also runs ruff when present).

Exit status: 0 clean, 1 violations/stale allowlist entries, 2 usage or
parse errors — CI-gate friendly.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .engine import default_config, run_analysis


def _changed_files(repo_root: str) -> list:
    """Working-tree .py AND .c files changed vs HEAD, plus untracked
    ones — the native sources ride the same pre-commit fast path as the
    Python ones."""
    def git(*args):
        r = subprocess.run(["git", "-C", repo_root] + list(args),
                           capture_output=True, text=True)
        return r.stdout.splitlines() if r.returncode == 0 else []

    names = set(git("diff", "--name-only", "HEAD")) | \
        set(git("ls-files", "--others", "--exclude-standard"))
    return sorted(n for n in names
                  if n.endswith(".py") or n.endswith(".c"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sctlint",
        description="Determinism & thread-discipline analyzer: Python "
                    "rules D1/D2/T1/E1/F1/M1, native C rules N1-N4, "
                    "admin-surface rule A1 — docs/static-analysis.md")
    ap.add_argument("files", nargs="*",
                    help="restrict per-module rules to these .py/.c "
                         "files (default: whole package)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only .py/.c files changed vs HEAD "
                         "(plus untracked)")
    ap.add_argument("--native", action="store_true",
                    help="run only the native C rules (N1-N4) over "
                         "native/*.c — the fast pre-commit gate for "
                         "engine changes")
    ap.add_argument("--repo-root", default=None)
    ap.add_argument("--list", action="store_true", dest="list_all",
                    help="print every finding (all rules, N/A ones "
                         "included) before allowlist filtering")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one machine-readable JSON object "
                         "(findings, violations, stale entries, cache "
                         "counters) instead of the human lines; exit "
                         "codes unchanged")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the build/sctlint-cache facts cache "
                         "(forces a full re-parse)")
    args = ap.parse_args(argv)

    cfg = default_config(args.repo_root)
    if args.no_cache:
        cfg.cache_dir = None
    if args.native:
        cfg.enabled_rules = tuple(
            r for r in cfg.enabled_rules if r.startswith("N"))
        if not cfg.enabled_rules:
            print("sctlint: --native but no N rules enabled "
                  "(pyproject [tool.sctlint] rules)")
            return 2
    files = args.files or None
    if args.changed:
        files = _changed_files(cfg.repo_root)
        if args.native:
            files = [f for f in files if f.endswith(".c")]
        if not files:
            print("sctlint: no changed files")
            return 0

    res = run_analysis(cfg, files=files)

    if args.as_json:
        def row(f):
            return {"rule": f.rule, "path": f.path, "line": f.line,
                    "qualname": f.qualname, "message": f.message}
        print(json.dumps({
            "ok": res.ok,
            "findings": [row(f) for f in res.findings],
            "violations": [row(f) for f in res.violations],
            "parse_errors": list(res.parse_errors),
            "stale_entries": [
                {"rule": e.rule, "path": e.path, "qual": e.qual,
                 "lineno": e.lineno} for e in res.stale_entries],
            "cache": {"hits": res.cache_hits,
                      "misses": res.cache_misses},
        }, indent=2, sort_keys=True))
        if res.parse_errors:
            return 2
        return 0 if res.ok else 1

    if args.list_all:
        for f in res.findings:
            print(f.format())
        print("-- %d finding(s) before allowlist --" % len(res.findings))

    for err in res.parse_errors:
        print("PARSE-ERROR %s" % err)
    for f in res.violations:
        print(f.format())
    for e in res.stale_entries:
        print("STALE-ALLOWLIST %s:%d: '%s %s%s' matched no finding — "
              "remove or fix the entry"
              % (cfg.allowlist_path, e.lineno, e.rule, e.path,
                 ("#" + e.qual) if e.qual else ""))

    if res.parse_errors:
        return 2
    if res.violations or res.stale_entries:
        print("sctlint: %d violation(s), %d stale allowlist entr(ies)"
              % (len(res.violations), len(res.stale_entries)))
        return 1
    scope = "%d file(s)" % len(files) if files else "whole package"
    print("sctlint: clean (%s; %d finding(s) allowlisted)"
          % (scope, len(res.findings) - len(res.violations)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
