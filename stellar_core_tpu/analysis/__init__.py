"""sctlint: the project's determinism & thread-discipline analyzer.

The framework's correctness story — per-height header-hash equality
across nodes, seeded chaos soaks, virtual-clock replay — rests on
invariants that generic linters cannot see. This package enforces them
mechanically, as an AST pass with project-specific rules:

- **D1** no wall-clock reads (`time.time` / `time.monotonic` /
  `time.perf_counter` / `datetime.now` / …) outside the clock
  abstraction and the measurement layer: consensus code gets time from
  the injected VirtualClock, so a virtual-clock replay is bit-exact.
- **D2** no unseeded randomness (`random.*` module-level functions,
  argless `random.Random()`, `os.urandom`) outside `util/rnd.py` and
  key generation: chaos runs replay from their seed.
- **T1** thread discipline: call-graph walk from every
  `threading.Thread(target=...)` / `executor.submit(...)` entry point;
  reaching a `@main_thread_only`-marked function (util/threads.py
  registry) is a violation — worker threads hand results to consensus
  via `clock.post_to_main`, never by calling in.
- **E1** no `except Exception: pass` in `scp/`, `herder/`, `ledger/`,
  `bucket/`: consensus code never swallows silently.
- **F1** every fault-site literal (`should_fire("...")`,
  `fire_point("...")`, `check_faults(x, "...")`) must be registered in
  `util.faults.KNOWN_SITES` and cataloged in docs/robustness.md — both
  directions — so the admin endpoint can reject typo'd sites and the
  chaos docs can never rot.
- **M1** every literal metric name registered via `new_counter` /
  `new_meter` / `new_timer` / `new_histogram` must appear in
  docs/metrics.md (dynamic `%s` names by their literal prefix).
- **A1** every `cmd_*` handler in main/command_handler.py has a row in
  the docs/admin.md endpoint table and vice versa.

Plus the native C rules (crules.py — a purpose-built C tokenizer +
call-graph pass over `native/*.c`, since the GIL-released pthread
engine is invisible to `ast`):

- **N1** no CPython API (`Py*`/`_Py*`) calls reachable with the GIL
  released (pthread worker entries + ALLOW_THREADS brackets; the
  returning `if (...->nopy)` guard idiom honored and required).
- **N2** no `malloc`/`free` family on the cluster-apply hot path —
  per-op buffers go through the per-context bump arenas.
- **N3** every `pthread_mutex_lock` balanced by an unlock on every
  return path (branch-aware structured path analysis).
- **N4** cross-boundary registries: C/Python bail-reason literals ⇄
  the docs/observability.md taxonomy table ⇄ test_apply_cockpit.py,
  and the C `OP_*` table ⇄ the Python `ledger.apply.op.<type>` names.

Intentional exceptions live in `analysis/allowlist.txt`, one line per
(rule, file) with a mandatory justification; stale entries fail the
build. The whole pass runs as tier-1 test `tests/test_static_analysis.py`
and standalone as `python -m stellar_core_tpu.analysis` (`tools/sctlint`;
`--native` for the N-rules-only fast gate). The runtime twin of the N
rules is the ThreadSanitizer leg (tests/test_native_sanitized.py).
See docs/static-analysis.md.
"""

from .engine import (  # noqa: F401
    AllowEntry, AnalysisResult, Finding, LintConfig, default_config,
    load_allowlist, run_analysis,
)
