"""Content-addressed cache for sctlint's per-file work (ISSUE 20
satellite): parsed facts (ModuleFacts/FlowFacts/CFileFacts) and the
per-file rule findings computed from them, keyed by
(engine-digest, config-digest, path, content-sha) and stored as one
pickle per key under `build/sctlint-cache/`.

Keying discipline — every input that can change a file's findings is in
the key, so there is no explicit invalidation protocol at all:

- the file's own content (sha256);
- the ENGINE digest: sha256 over the sources of the analysis package
  itself, so editing any rule invalidates the whole cache (a linter
  that serves stale verdicts after a rule change is worse than a slow
  one);
- the CONFIG digest: the per-module knobs (enabled rules, e1/s1/fl1
  dirs, package name) — flipping a pyproject stanza re-lints.

Failure stance: the cache is an accelerator, never a correctness
dependency. Any OSError/pickle error on read counts as a miss; any
error on write is swallowed; a corrupt entry is deleted and recomputed.
Hit/miss counters are exported on AnalysisResult so tests assert the
warm-run speedup structurally (hits == files) instead of wall-clock.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Optional

_PICKLE_PROTO = 4


def file_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def engine_digest() -> str:
    """Digest of the analysis package's own sources — rule edits
    invalidate every cached verdict."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for fn in sorted(os.listdir(here)):
        if not fn.endswith(".py"):
            continue
        h.update(fn.encode("utf-8"))
        try:
            with open(os.path.join(here, fn), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(b"<unreadable>")
    return h.hexdigest()


class SctlintCache:
    """One pickle per (path, content, engine, config) key. `None` dir
    disables caching entirely (fixture runs stay hermetic)."""

    # entries kept before mtime-based pruning kicks in; the tree is a
    # few hundred files, so this allows ~8 generations of full-tree
    # churn before any eviction happens at all
    MAX_ENTRIES = 4096

    def __init__(self, cache_dir: Optional[str],
                 config_digest: str = "") -> None:
        self.dir = cache_dir
        self.hits = 0
        self.misses = 0
        self._prefix = ""
        if cache_dir is not None:
            self._prefix = engine_digest()[:16] + config_digest[:16]
            try:
                os.makedirs(cache_dir, exist_ok=True)
            except OSError:
                self.dir = None

    def key_for(self, rel_path: str, data: bytes) -> str:
        h = hashlib.sha256()
        h.update(self._prefix.encode("ascii"))
        h.update(rel_path.encode("utf-8"))
        h.update(b"\x00")
        h.update(data)
        return h.hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, key + ".pkl")

    def get(self, key: str):
        """Cached object or None; every failure mode is a miss."""
        if self.dir is None:
            return None
        p = self._path(key)
        try:
            with open(p, "rb") as fh:
                obj = pickle.load(fh)
        except (OSError, pickle.UnpicklingError, EOFError,
                AttributeError, ImportError, IndexError, ValueError):
            try:
                if os.path.exists(p):
                    os.unlink(p)   # corrupt entry: recompute, re-store
            except OSError:
                pass
            self.misses += 1
            return None
        self.hits += 1
        return obj

    def put(self, key: str, obj) -> None:
        if self.dir is None:
            return
        p = self._path(key)
        tmp = p + ".tmp.%d" % os.getpid()
        try:
            with open(tmp, "wb") as fh:
                pickle.dump(obj, fh, protocol=_PICKLE_PROTO)
            os.replace(tmp, p)      # atomic: parallel runs never see torn
        except (OSError, pickle.PicklingError, TypeError):
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass

    def prune(self) -> None:
        """Drop oldest entries past MAX_ENTRIES (stale generations from
        edited files/engines accumulate; content-keying never reuses
        them, so they are pure disk waste)."""
        if self.dir is None:
            return
        try:
            names = [n for n in os.listdir(self.dir)
                     if n.endswith(".pkl")]
            if len(names) <= self.MAX_ENTRIES:
                return
            with_mtime = []
            for n in names:
                p = os.path.join(self.dir, n)
                try:
                    with_mtime.append((os.path.getmtime(p), p))
                except OSError:
                    pass
            with_mtime.sort()
            for _, p in with_mtime[:len(with_mtime) - self.MAX_ENTRIES]:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        except OSError:
            pass
