"""C-side concurrency-discipline rules (N1–N4) for sctlint.

PR 12 moved the apply hot path into `native/applyc.c`: a ~5.6k-line
engine that applies disjoint transaction clusters on a detached pthread
pool with the GIL released. The Python rules (D1–M1, rules.py) cannot
see any of it, yet the native layer carries the same classes of
invariant — thread discipline, allocation discipline, registry/doc
parity — that sctlint exists to enforce. This module is T1/F1/M1's
approach ported across the language boundary, on a purpose-built C
tokenizer instead of `ast`:

- **N1 — no CPython in GIL-released code.** Regions are (a) everything
  reachable from a pthread worker entry point (the 3rd argument of
  `pthread_create`) and (b) calls bracketed by
  `Py_BEGIN_ALLOW_THREADS`/`Py_END_ALLOW_THREADS` (or
  `PyEval_SaveThread`/`PyEval_RestoreThread`). A call-graph walk from
  those roots flags any reachable `Py*`/`_Py*` call. The engine's own
  escape idiom is honored and ENFORCED: a function may contain Python
  calls after an `if (...->nopy) { ... return/goto ...; }` guard —
  everything past a returning nopy-guard only runs with the GIL held —
  but a reachable Py* call with no guard before it is a violation.
- **N2 — allocation discipline.** The same reachability set must not
  call `malloc`/`calloc`/`realloc`/`free` (&co): per-op buffers on the
  hot path go through the per-context bump arenas (`arena_alloc`),
  whose own block `malloc` is the one sanctioned allocator
  (`ARENA_FUNCS` below). Deliberate amortized-growth remainders are
  allowlist lines, not silent exemptions.
- **N3 — lock balance.** Structured path analysis per function: every
  `pthread_mutex_lock` must be matched by an unlock on every return
  path (per-mutex, branch-aware, loop bodies must be net-zero;
  `pthread_cond_wait` is net-zero by contract). Functions mixing
  mutexes with `goto` are flagged as unanalyzable rather than guessed
  at.
- **N4 — cross-boundary registries.** (a) Every C bail-reason literal
  (`ctx_bail`/`env_bail`, plus `snprintf`-into-`bailbuf` dynamic
  prefixes) and every Python-side `_bail(...)` literal must have a row
  in the "Native bail taxonomy" table in docs/observability.md, every
  row must have a live call site, and the taxonomy must stay exercised
  by tests/test_apply_cockpit.py. (b) The engine's `#define OP_*` op
  table must cover exactly the wire op types the Python
  `ledger.apply.op.<type>` name table knows (no `unknown-N` metric
  names possible), with the dynamic prefix documented in
  docs/metrics.md.

Like the Python rules, everything over-approximates in the safe
direction: a false edge costs an allowlist line with a justification, a
missed edge is a data race or a GIL crash found in production.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import Finding

# the sanctioned hot-path allocator: its block malloc/free IS the arena
ARENA_FUNCS = {"arena_alloc", "arena_free_all"}
ALLOC_FUNCS = {"malloc", "calloc", "realloc", "free", "strdup",
               "aligned_alloc", "posix_memalign", "reallocarray"}
# GIL bracket macros/calls: region delimiters, never themselves findings
_GIL_BEGIN = {"Py_BEGIN_ALLOW_THREADS", "PyEval_SaveThread"}
_GIL_END = {"Py_END_ALLOW_THREADS", "PyEval_RestoreThread"}
_PY_CALL_RE = re.compile(r"^_?Py[A-Z_]")
_C_KEYWORDS = {"if", "else", "for", "while", "do", "switch", "case",
               "default", "return", "break", "continue", "goto",
               "sizeof", "struct", "union", "enum", "typedef", "static",
               "const", "volatile", "register", "extern", "inline"}

_LOCK_CALLS = {"pthread_mutex_lock": 1, "pthread_mutex_unlock": -1,
               "pthread_spin_lock": 1, "pthread_spin_unlock": -1}
_COND_WAITS = {"pthread_cond_wait", "pthread_cond_timedwait"}


class Tok:
    __slots__ = ("kind", "val", "line")

    def __init__(self, kind: str, val: str, line: int) -> None:
        self.kind = kind
        self.val = val
        self.line = line

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "Tok(%s,%r,%d)" % (self.kind, self.val, self.line)


def tokenize_c(text: str) -> Tuple[List[Tok], List[Tuple[int, str]]]:
    """C token stream (comments dropped, strings kept as single tokens)
    plus the preprocessor directives as (line, folded-text) pairs."""
    toks: List[Tok] = []
    directives: List[Tuple[int, str]] = []
    i, n, line = 0, len(text), 1
    ident = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
    num = re.compile(r"(?:0[xX][0-9a-fA-F]+|\d+(?:\.\d*)?(?:[eE][+-]?\d+)?)"
                     r"[uUlLfF]*")
    while i < n:
        ch = text[i]
        if ch == "\n":
            line += 1
            i += 1
            continue
        if ch in " \t\r\v\f":
            i += 1
            continue
        if text.startswith("//", i):
            j = text.find("\n", i)
            i = n if j < 0 else j
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                raise ValueError("line %d: unterminated /* comment" % line)
            line += text.count("\n", i, j)
            i = j + 2
            continue
        if ch == "#" and (not toks or toks[-1].line != line):
            # preprocessor directive: consume to EOL honoring \-continuation
            start, parts = line, []
            while i < n:
                j = text.find("\n", i)
                j = n if j < 0 else j
                seg = text[i:j]
                i = j + 1
                line += 1
                if seg.endswith("\\"):
                    parts.append(seg[:-1])
                    continue
                parts.append(seg)
                break
            directives.append((start, " ".join(parts)))
            continue
        if ch in "\"'":
            q, j, start_line = ch, i + 1, line
            while j < n:
                if text[j] == "\\":
                    # a \<newline> continuation inside the literal must
                    # still count the line, or every later token's
                    # reported line (and allowlist diagnostics) drifts
                    if j + 1 < n and text[j + 1] == "\n":
                        line += 1
                    j += 2
                    continue
                if text[j] == q:
                    break
                if text[j] == "\n":
                    raise ValueError("line %d: unterminated %s literal"
                                     % (line, "string" if q == '"'
                                        else "char"))
                j += 1
            if j >= n:
                raise ValueError("line %d: unterminated literal" % line)
            toks.append(Tok("str" if q == '"' else "char",
                            text[i + 1:j], start_line))
            i = j + 1
            continue
        m = ident.match(text, i)
        if m:
            toks.append(Tok("id", m.group(), line))
            i = m.end()
            continue
        m = num.match(text, i)
        if m:
            toks.append(Tok("num", m.group(), line))
            i = m.end()
            continue
        toks.append(Tok("punct", ch, line))
        i += 1
    return toks, directives


def _match_close(toks: Sequence[Tok], i: int, open_c: str,
                 close_c: str) -> int:
    """Index of the punct closing the one at i (assumes toks[i] opens)."""
    depth = 0
    for j in range(i, len(toks)):
        t = toks[j]
        if t.kind == "punct":
            if t.val == open_c:
                depth += 1
            elif t.val == close_c:
                depth -= 1
                if depth == 0:
                    return j
    raise ValueError("line %d: unbalanced %r" % (toks[i].line, open_c))


def _stmt_end(toks: Sequence[Tok], k: int) -> int:
    """Index of the token ending the single statement starting at k:
    the top-level `;`, or — for a brace-less compound statement like
    `if (x) while (y) { ... }` — the `}` closing its block (such a
    statement has no terminating semicolon). Nesting depth is honored
    (a `;` inside a for-header doesn't end it). Returns len(toks) when
    the statement runs off the slice."""
    depth = 0
    while k < len(toks):
        t = toks[k]
        if t.kind == "punct":
            if t.val == "{" and depth == 0:
                return _match_close(toks, k, "{", "}")
            if t.val in "([{":
                depth += 1
            elif t.val in ")]}":
                depth -= 1
            elif t.val == ";" and depth == 0:
                return k
        k += 1
    return k


def call_args(toks: Sequence[Tok], open_paren: int) -> List[List[Tok]]:
    """Split the argument list of a call whose '(' is at open_paren into
    top-level-comma-separated token slices."""
    close = _match_close(toks, open_paren, "(", ")")
    args: List[List[Tok]] = []
    cur: List[Tok] = []
    depth = 0
    for j in range(open_paren + 1, close):
        t = toks[j]
        if t.kind == "punct":
            if t.val in "([{":
                depth += 1
            elif t.val in ")]}":
                depth -= 1
            elif t.val == "," and depth == 0:
                args.append(cur)
                cur = []
                continue
        cur.append(t)
    if cur or args:
        args.append(cur)
    return args


class CFunc:
    """One function definition: its body token slice plus the derived
    facts every N-rule consumes."""

    def __init__(self, path: str, name: str, line: int,
                 body: List[Tok]) -> None:
        self.path = path
        self.name = name
        self.line = line
        self.body = body
        # ordered calls: (body_idx, name, line)
        self.calls: List[Tuple[int, str, int]] = []
        self.py_calls: List[Tuple[int, str, int]] = []
        self.alloc_calls: List[Tuple[int, str, int]] = []
        self.gil_regions: List[Tuple[int, int]] = []
        self.nopy_guard_end: Optional[int] = None  # body idx after guard
        self.thread_targets: List[Tuple[str, int]] = []  # (fn, line)
        self._derive()

    def _derive(self) -> None:
        toks = self.body
        begin_at: Optional[int] = None
        for i, t in enumerate(toks):
            if t.kind != "id":
                continue
            nxt = toks[i + 1] if i + 1 < len(toks) else None
            is_call = nxt is not None and nxt.kind == "punct" and \
                nxt.val == "("
            if t.val in _GIL_BEGIN:
                if begin_at is None:
                    begin_at = i
                continue
            if t.val in _GIL_END:
                if begin_at is not None:
                    self.gil_regions.append((begin_at, i))
                    begin_at = None
                continue
            if not is_call or t.val in _C_KEYWORDS:
                continue
            self.calls.append((i, t.val, t.line))
            if _PY_CALL_RE.match(t.val):
                self.py_calls.append((i, t.val, t.line))
            if t.val in ALLOC_FUNCS:
                self.alloc_calls.append((i, t.val, t.line))
            if t.val == "pthread_create":
                args = call_args(toks, i + 1)
                if len(args) >= 3:
                    target = [a for a in args[2] if a.kind == "id"]
                    if target:
                        self.thread_targets.append(
                            (target[-1].val, t.line))
        if begin_at is not None:
            # unmatched begin: treat the rest of the body as the region
            self.gil_regions.append((begin_at, len(toks) - 1))
        self._find_nopy_guard()

    def _find_nopy_guard(self) -> None:
        """The engine's GIL-escape idiom: `if (...nopy...) { ...;
        return/goto; }`. Everything after a RETURNING guard only runs
        with the GIL held, so the nogil walk stops there. A guard that
        falls through guards nothing — and neither does an INVERTED
        test (`if (!c->nopy) return;` returns exactly when the GIL is
        held, so the code after it is the nogil path)."""
        toks = self.body
        for i, t in enumerate(toks):
            if t.kind != "id" or t.val != "nopy":
                continue
            # the nopy access must sit inside an if-CONDITION: walking
            # backward we must reach `if` before any statement/block
            # boundary (an assignment like `c.nopy = 1` is no guard)
            k = i - 1
            in_if = False
            while k >= 0:
                tk = toks[k]
                if tk.kind == "id" and tk.val == "if":
                    in_if = True
                    break
                if tk.kind == "punct" and tk.val in (";", "{", "}"):
                    break
                k -= 1
            if not in_if:
                return
            # polarity: a `!` anywhere before the access chain, or a
            # trailing `== 0`, inverts the test — not a nogil guard
            if any(toks[m].kind == "punct" and toks[m].val == "!"
                   for m in range(k + 1, i)):
                return
            # find the enclosing if-condition's close paren
            j = i
            depth = 0
            while j < len(toks):
                tj = toks[j]
                if tj.kind == "punct":
                    if tj.val == "(":
                        depth += 1
                    elif tj.val == ")":
                        if depth == 0:
                            break
                        depth -= 1
                j += 1
            if j >= len(toks):
                return
            # the condition must be the BARE truthy nopy access — an
            # identifier chain of `.`/`->` only. A compound test
            # (`c->nopy && x`) can fall through with nopy set; a
            # comparison (`== 0`, Yoda `0 == ...`) may invert it; a
            # call wrapper (`invert(c->nopy)`) can do anything. So any
            # token besides ids and `-`/`>`/`.` puncts disqualifies
            # the guard — over-reject in the safe direction (an
            # unhonored real guard costs an allowlist line; an honored
            # fake one is a GIL crash).
            cond_lo = k + 1  # the `(` after `if`
            for m in range(cond_lo + 1, j):
                tm = toks[m]
                if tm.kind == "id":
                    continue
                if tm.kind == "punct" and tm.val in ("-", ">", "."):
                    continue
                return
            # guard body: block or single statement
            k = j + 1
            if k < len(toks) and toks[k].kind == "punct" and \
                    toks[k].val == "{":
                end = _match_close(toks, k, "{", "}")
            else:
                end = _stmt_end(toks, k)
            body = toks[k:end + 1]
            if any(b.kind == "id" and b.val in ("return", "goto")
                   for b in body):
                self.nopy_guard_end = end
            return  # only the FIRST nopy reference is the guard point

    def nogil_calls(self) -> List[Tuple[int, str, int]]:
        """Calls that can run with the GIL released: everything up to
        the end of a returning nopy guard, or all calls without one."""
        if self.nopy_guard_end is None:
            return self.calls
        return [c for c in self.calls if c[0] <= self.nopy_guard_end]

    def nogil_py_calls(self) -> List[Tuple[int, str, int]]:
        if self.nopy_guard_end is None:
            return self.py_calls
        return [c for c in self.py_calls if c[0] <= self.nopy_guard_end]

    def nogil_alloc_calls(self) -> List[Tuple[int, str, int]]:
        if self.nopy_guard_end is None:
            return self.alloc_calls
        return [c for c in self.alloc_calls if c[0] <= self.nopy_guard_end]


class CFileFacts:
    """Single-pass fact collector for one C translation unit."""

    def __init__(self, path: str, text: str) -> None:
        self.path = path
        self.toks, self.directives = tokenize_c(text)
        self.functions: Dict[str, CFunc] = {}
        self.defines: Dict[str, str] = {}
        self._collect_defines()
        self._collect_functions()

    def _collect_defines(self) -> None:
        d_re = re.compile(r"#\s*define\s+([A-Za-z_]\w*)\s+(.+?)\s*$")
        for (_line, text) in self.directives:
            m = d_re.match(text)
            if m and "(" not in m.group(1):
                # object-like macros only; strip trailing comments
                val = m.group(2).split("/*")[0].strip()
                self.defines[m.group(1)] = val

    def _collect_functions(self) -> None:
        toks = self.toks
        i = 0
        while i < len(toks):
            t = toks[i]
            if t.kind == "punct" and t.val == "{":
                # top-level non-function brace (struct body, initializer)
                i = _match_close(toks, i, "{", "}") + 1
                continue
            if t.kind == "id" and t.val not in _C_KEYWORDS and \
                    i + 1 < len(toks) and toks[i + 1].kind == "punct" and \
                    toks[i + 1].val == "(":
                close = _match_close(toks, i + 1, "(", ")")
                j = close + 1
                if j < len(toks) and toks[j].kind == "punct" and \
                        toks[j].val == "{":
                    end = _match_close(toks, j, "{", "}")
                    fn = CFunc(self.path, t.val, t.line, toks[j:end + 1])
                    # first definition wins (C forbids dups per TU anyway)
                    self.functions.setdefault(t.val, fn)
                    i = end + 1
                    continue
                i = close + 1
                continue
            i += 1

    def thread_entries(self) -> List[Tuple[str, str, int]]:
        """(target_fn, spawning_fn, line) for every pthread_create."""
        out = []
        for fn in self.functions.values():
            for (target, line) in fn.thread_targets:
                out.append((target, fn.name, line))
        return out


# --------------------------------------------------------------------------
# N1/N2: the nogil reachability walk


def _nogil_roots(facts: CFileFacts) -> Dict[str, str]:
    """Function name -> provenance string for every nogil root: pthread
    entry points and calls made inside GIL-released brackets."""
    roots: Dict[str, str] = {}
    for (target, spawner, line) in facts.thread_entries():
        roots.setdefault(
            target, "pthread worker entry (pthread_create in %s:%d)"
            % (spawner, line))
    for fn in facts.functions.values():
        for (lo, hi) in fn.gil_regions:
            for (idx, name, line) in fn.calls:
                if lo < idx < hi and name not in _GIL_BEGIN and \
                        name not in _GIL_END:
                    roots.setdefault(
                        name, "GIL-released bracket in %s:%d"
                        % (fn.name, line))
    return roots


def _walk_nogil(facts: CFileFacts, max_depth: int = 24
                ) -> Dict[str, Tuple[str, Tuple[str, ...]]]:
    """BFS over nogil-visible call edges; returns
    {reached_fn: (provenance, chain)}. Memoized per CFileFacts — N1
    and N2 share one walk per translation unit."""
    from collections import deque

    cached = getattr(facts, "_nogil_walk", None)
    if cached is not None:
        return cached

    roots = _nogil_roots(facts)
    reached: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
    frontier: deque = deque()
    for name, why in sorted(roots.items()):
        if name in facts.functions and name not in reached:
            reached[name] = (why, (name,))
            frontier.append(name)
    while frontier:
        cur = frontier.popleft()
        why, chain = reached[cur]
        if len(chain) > max_depth:
            continue
        for (_idx, callee, _line) in facts.functions[cur].nogil_calls():
            if callee in facts.functions and callee not in reached:
                reached[callee] = (why, chain + (callee,))
                frontier.append(callee)
    facts._nogil_walk = reached
    return reached


def rule_n1_nogil_python(facts: CFileFacts) -> List[Finding]:
    out: List[Finding] = []
    # direct Py* calls lexically inside a GIL-released bracket
    for fn in facts.functions.values():
        for (lo, hi) in fn.gil_regions:
            for (idx, name, line) in fn.py_calls:
                if lo < idx < hi:
                    out.append(Finding(
                        "N1", facts.path, line, fn.name,
                        "CPython call `%s` inside a GIL-released "
                        "bracket — the GIL is NOT held here" % name))
    for name, (why, chain) in sorted(_walk_nogil(facts).items()):
        fn = facts.functions[name]
        for (_idx, pyname, line) in fn.nogil_py_calls():
            out.append(Finding(
                "N1", facts.path, line, fn.name,
                "CPython call `%s` reachable with the GIL released "
                "[%s via %s] — guard it behind the returning "
                "`if (...->nopy)` idiom or keep Python out of the "
                "worker path" % (pyname, why, " -> ".join(chain))))
    return out


def rule_n2_alloc_discipline(facts: CFileFacts) -> List[Finding]:
    out: List[Finding] = []
    # direct allocator calls lexically inside a GIL-released bracket
    # (same scan as N1's direct-bracket pass — the bracketed region IS
    # the hot path even when its host function is no worker entry)
    for fn in facts.functions.values():
        if fn.name in ARENA_FUNCS:
            continue
        for (lo, hi) in fn.gil_regions:
            for (idx, alloc, line) in fn.alloc_calls:
                if lo < idx < hi:
                    out.append(Finding(
                        "N2", facts.path, line, fn.name,
                        "heap call `%s` inside a GIL-released bracket "
                        "— per-op buffers go through the per-context "
                        "bump arena (arena_alloc)" % alloc))
    for name, (why, chain) in sorted(_walk_nogil(facts).items()):
        if name in ARENA_FUNCS:
            continue  # the arena implementation IS the allocator
        fn = facts.functions[name]
        for (_idx, alloc, line) in fn.nogil_alloc_calls():
            out.append(Finding(
                "N2", facts.path, line, fn.name,
                "heap call `%s` on the cluster-apply hot path [%s via "
                "%s] — per-op buffers go through the per-context bump "
                "arena (arena_alloc)" % (alloc, why, " -> ".join(chain))))
    return out


# --------------------------------------------------------------------------
# N3: structured lock-balance analysis


class _LockEval:
    """Branch-aware, per-mutex lock-depth evaluation over one function
    body. States are frozensets of (mutex_key, depth) pairs; the
    evaluator computes the set of possible states at every `return` and
    at the implicit end-of-function, plus net-delta checks across loop
    bodies."""

    MAX_STATES = 64

    def __init__(self, fn: CFunc, path: str) -> None:
        self.fn = fn
        self.path = path
        self.findings: List[Finding] = []
        self._reported: Set[Tuple[str, int]] = set()

    # -- state helpers ------------------------------------------------------
    @staticmethod
    def _adjust(state: frozenset, key: str, delta: int) -> frozenset:
        d = dict(state)
        d[key] = d.get(key, 0) + delta
        if d[key] == 0:
            del d[key]
        return frozenset(d.items())

    def _flag(self, line: int, msg: str) -> None:
        k = (msg, line)
        if k not in self._reported:
            self._reported.add(k)
            self.findings.append(
                Finding("N3", self.path, line, self.fn.name, msg))

    def _held(self, state: frozenset) -> List[str]:
        return sorted(k for (k, v) in state if v > 0)

    # -- driver -------------------------------------------------------------
    def run(self) -> List[Finding]:
        toks = self.fn.body
        if any(t.kind == "id" and t.val == "goto" for t in toks) and \
                any(t.kind == "id" and t.val in _LOCK_CALLS for t in toks):
            self._flag(self.fn.line,
                       "mixes pthread mutex calls with `goto` — lock "
                       "balance is not statically analyzable here; "
                       "restructure or allowlist with a justification")
            return self.findings
        body = toks[1:-1] if toks and toks[0].val == "{" else toks
        ends, _brk, _cont = self._eval(body, {frozenset()})
        for st in ends:
            held = self._held(st)
            if held:
                self._flag(self.fn.line,
                           "falls off the end still holding %s"
                           % ", ".join("`%s`" % h for h in held))
        return self.findings

    def _eval(self, toks: List[Tok], states: Set[frozenset]
              ) -> Tuple[Set[frozenset], Set[frozenset], Set[frozenset]]:
        """Evaluate a statement sequence. Returns (fallthrough states,
        break states, continue states)."""
        breaks: Set[frozenset] = set()
        continues: Set[frozenset] = set()
        i = 0
        while i < len(toks) and states:
            t = toks[i]
            if t.kind == "punct" and t.val == "{":
                end = _match_close(toks, i, "{", "}")
                states, b, c = self._eval(toks[i + 1:end], states)
                breaks |= b
                continues |= c
                i = end + 1
                continue
            if t.kind == "id" and t.val == "if":
                i, states, b, c = self._eval_if(toks, i, states)
                breaks |= b
                continues |= c
                continue
            if t.kind == "id" and t.val in ("while", "for"):
                i, states = self._eval_loop(toks, i, states)
                continue
            if t.kind == "id" and t.val == "do":
                i, states = self._eval_do(toks, i, states)
                continue
            if t.kind == "id" and t.val == "switch":
                i, states, c = self._eval_switch(toks, i, states)
                continues |= c   # continue passes through to the loop
                continue
            if t.kind == "id" and t.val == "return":
                for st in states:
                    held = self._held(st)
                    if held:
                        self._flag(t.line,
                                   "return path still holds %s"
                                   % ", ".join("`%s`" % h for h in held))
                return set(), breaks, continues
            if t.kind == "id" and t.val == "break":
                breaks |= states
                return set(), breaks, continues
            if t.kind == "id" and t.val == "continue":
                continues |= states
                return set(), breaks, continues
            if t.kind == "id" and t.val in _LOCK_CALLS and \
                    i + 1 < len(toks) and toks[i + 1].val == "(":
                key = self._mutex_key(toks, i + 1)
                delta = _LOCK_CALLS[t.val]
                nxt = set()
                for st in states:
                    ns = self._adjust(st, key, delta)
                    if delta < 0 and dict(ns).get(key, 0) < 0:
                        self._flag(t.line,
                                   "unlocks `%s` on a path where it is "
                                   "not held" % key)
                        ns = self._adjust(ns, key, 1)  # clamp, continue
                    nxt.add(ns)
                states = self._cap(nxt)
                i = _match_close(toks, i + 1, "(", ")") + 1
                continue
            if t.kind == "punct" and t.val == "(":
                i = _match_close(toks, i, "(", ")") + 1
                continue
            i += 1
        return states, breaks, continues

    def _cap(self, states: Set[frozenset]) -> Set[frozenset]:
        if len(states) > self.MAX_STATES:  # pragma: no cover - safety net
            states = set(sorted(states)[:self.MAX_STATES])
        return states

    def _mutex_key(self, toks: List[Tok], open_paren: int) -> str:
        args = call_args(toks, open_paren)
        if not args:
            return "<?>"
        return "".join(t.val for t in args[0] if t.val != "&")

    def _cond_and_body(self, toks: List[Tok], i: int
                       ) -> Tuple[List[Tok], int, int, int]:
        """For a construct at i with shape KW (cond) body: returns
        (cond tokens, body start, body end inclusive, next index)."""
        j = i + 1
        if not (j < len(toks) and toks[j].kind == "punct" and
                toks[j].val == "("):
            return [], i + 1, i, i + 1
        close = _match_close(toks, j, "(", ")")
        cond = toks[j + 1:close]
        k = close + 1
        if k < len(toks) and toks[k].kind == "punct" and toks[k].val == "{":
            end = _match_close(toks, k, "{", "}")
            return cond, k + 1, end - 1, end + 1
        end = _stmt_end(toks, k)
        return cond, k, end, end + 1

    def _eval_if(self, toks: List[Tok], i: int, states: Set[frozenset]
                 ) -> Tuple[int, Set[frozenset], Set[frozenset],
                            Set[frozenset]]:
        _cond, b0, b1, nxt = self._cond_and_body(toks, i)
        then_states, brk, cont = self._eval(toks[b0:b1 + 1], set(states))
        if nxt < len(toks) and toks[nxt].kind == "id" and \
                toks[nxt].val == "else":
            e = nxt + 1
            if e < len(toks) and toks[e].kind == "id" and toks[e].val == "if":
                e2, else_states, b2, c2 = self._eval_if(toks, e, set(states))
                return e2, then_states | else_states, brk | b2, cont | c2
            if e < len(toks) and toks[e].kind == "punct" and \
                    toks[e].val == "{":
                end = _match_close(toks, e, "{", "}")
                else_states, b2, c2 = self._eval(toks[e + 1:end],
                                                 set(states))
                return end + 1, then_states | else_states, \
                    brk | b2, cont | c2
            end = _stmt_end(toks, e)
            else_states, b2, c2 = self._eval(toks[e:end + 1], set(states))
            return end + 1, then_states | else_states, brk | b2, cont | c2
        return nxt, self._cap(then_states | states), brk, cont

    @staticmethod
    def _infinite(kw: str, cond: List[Tok]) -> bool:
        if kw == "for":
            # for (a; COND; b): infinite when COND is empty
            depth = 0
            semis = []
            for idx, t in enumerate(cond):
                if t.kind == "punct":
                    if t.val in "([{":
                        depth += 1
                    elif t.val in ")]}":
                        depth -= 1
                    elif t.val == ";" and depth == 0:
                        semis.append(idx)
            if len(semis) == 2:
                return semis[1] - semis[0] == 1
            return False
        return len(cond) == 1 and cond[0].val in ("1", "true")

    def _eval_loop(self, toks: List[Tok], i: int, states: Set[frozenset]
                   ) -> Tuple[int, Set[frozenset]]:
        kw = toks[i].val
        cond, b0, b1, nxt = self._cond_and_body(toks, i)
        body_states, brk, cont = self._eval(toks[b0:b1 + 1], set(states))
        # `continue` rejoins the loop head: its states are iteration
        # outcomes too, so a lock leaked on a continue path is the same
        # across-iteration imbalance as one leaked at the body end
        body_states = body_states | cont
        for st in body_states:
            if st not in states:
                entry = next(iter(states)) if len(states) == 1 else None
                self._flag(toks[i].line,
                           "lock imbalance across a loop iteration "
                           "(body net-changes held locks%s)"
                           % ("" if entry is None else ": %s -> %s"
                              % (self._held(entry) or "[]",
                                 self._held(st) or "[]")))
        if self._infinite(kw, cond):
            return nxt, self._cap(brk)   # no fallthrough without a break
        return nxt, self._cap(states | body_states | brk)

    def _eval_do(self, toks: List[Tok], i: int, states: Set[frozenset]
                 ) -> Tuple[int, Set[frozenset]]:
        k = i + 1
        if k < len(toks) and toks[k].kind == "punct" and toks[k].val == "{":
            end = _match_close(toks, k, "{", "}")
            body_states, brk, cont = self._eval(toks[k + 1:end],
                                                set(states))
            body_states = body_states | cont  # continue = iteration end
            for st in body_states:
                if st not in states:
                    self._flag(toks[i].line,
                               "lock imbalance across a do-while "
                               "iteration")
            nxt = end + 1
            while nxt < len(toks) and not (toks[nxt].kind == "punct" and
                                           toks[nxt].val == ";"):
                nxt += 1
            return nxt + 1, self._cap(body_states | brk)
        return k, states

    def _eval_switch(self, toks: List[Tok], i: int, states: Set[frozenset]
                     ) -> Tuple[int, Set[frozenset], Set[frozenset]]:
        _cond, b0, b1, nxt = self._cond_and_body(toks, i)
        body = toks[b0:b1 + 1]
        # case dispatch is not straight-line: an early return/break
        # would hide later cases' lock ops from a linear scan. Any
        # mutex call INSIDE a switch is therefore declared unanalyzable
        # (the goto stance) rather than guessed at.
        if any(t.kind == "id" and t.val in _LOCK_CALLS for t in body):
            self._flag(toks[i].line,
                       "switch contains pthread mutex calls — "
                       "case-level lock balance is not statically "
                       "analyzable here; restructure or allowlist "
                       "with a justification")
            # neutralize lock state downstream: the single finding
            # above is the verdict; guessing on would double-report
            return nxt, {frozenset()}, set()
        # no lock ops inside: the body cannot change lock state, so
        # evaluation reduces to checking `return` against the entry
        # states (an early return in a case still exits holding
        # whatever the function holds) and propagating `continue`
        # (which belongs to the enclosing loop, not the switch)
        held = sorted({h for st in states for h in self._held(st)})
        if held:
            for t in body:
                if t.kind == "id" and t.val == "return":
                    self._flag(t.line,
                               "return path still holds %s"
                               % ", ".join("`%s`" % h for h in held))
        cont: Set[frozenset] = set()
        if any(t.kind == "id" and t.val == "continue" for t in body):
            cont = set(states)
        return nxt, states, cont


def rule_n3_lock_balance(facts: CFileFacts) -> List[Finding]:
    out: List[Finding] = []
    for fn in facts.functions.values():
        uses_lock = any(t.kind == "id" and t.val in _LOCK_CALLS
                        for t in fn.body)
        uses_wait = any(t.kind == "id" and t.val in _COND_WAITS
                        for t in fn.body)
        if uses_lock or uses_wait:
            out.extend(_LockEval(fn, facts.path).run())
    out.sort(key=lambda f: f.line)
    return out


# --------------------------------------------------------------------------
# N4: cross-boundary registries (bail taxonomy + op-type table)

_BAIL_CALLS = {"ctx_bail": 1, "env_bail": 1}  # literal arg index
_TAXONOMY_HEADING = "native bail taxonomy"
_ROW_RE = re.compile(r"^\|\s*`([^`|]+)`\s*\|\s*([^|]*)\|")


def native_bail_taxonomy(docs_text: str) -> Dict[str, str]:
    """Parse the "Native bail taxonomy" table out of
    docs/observability.md: {reason: origin}. `reason` may carry a
    `<...>` placeholder marking a dynamic family (`op-<type>`).
    Exposed publicly — tests/test_apply_cockpit.py exercises the same
    registry the N4 rule enforces."""
    out: Dict[str, str] = {}
    in_section = False
    for line in docs_text.splitlines():
        if line.startswith("#"):
            in_section = _TAXONOMY_HEADING in line.lower()
            continue
        if not in_section:
            continue
        m = _ROW_RE.match(line.strip())
        if m and m.group(1).strip() not in ("reason",):
            out[m.group(1).strip()] = m.group(2).strip().lower()
    return out


def _collect_c_bails(all_cfacts: Sequence[CFileFacts]
                     ) -> Tuple[List[Tuple[str, str, int, str]], Set[str]]:
    """([(path, reason, line, func)], {dynamic prefixes}) from
    ctx_bail/env_bail literals and snprintf-into-bailbuf formats."""
    literals: List[Tuple[str, str, int, str]] = []
    prefixes: Set[str] = set()
    for facts in all_cfacts:
        for fn in facts.functions.values():
            for (idx, name, line) in fn.calls:
                if name in _BAIL_CALLS:
                    args = call_args(fn.body, idx + 1)
                    if len(args) > _BAIL_CALLS[name]:
                        arg = args[_BAIL_CALLS[name]]
                        # pure literal arg, incl. adjacent-string
                        # concatenation ("liab-" "release")
                        if arg and all(t.kind == "str" for t in arg):
                            literals.append(
                                (facts.path,
                                 "".join(t.val for t in arg),
                                 line, fn.name))
                elif name == "snprintf":
                    args = call_args(fn.body, idx + 1)
                    if len(args) >= 3 and any(
                            t.val == "bailbuf" for t in args[0]):
                        fmt = [t for t in args[2] if t.kind == "str"]
                        if fmt:
                            prefixes.add(fmt[0].val.split("%")[0])
    return literals, prefixes


def rule_n4_cross_boundary(
        all_cfacts: Sequence[CFileFacts],
        py_bail_literals: Sequence[Tuple[str, int, str, str]],
        docs_obs_text: str, docs_obs_name: str,
        docs_metrics_text: str, docs_metrics_name: str,
        bail_test_text: Optional[str], bail_test_name: str,
        op_type_names: Optional[Dict[int, str]]) -> List[Finding]:
    """Registry parity across the C/Python boundary.

    `py_bail_literals`: (path, line, reason, qual) from the Python-side
    `_bail(stats, "...")` gates (collected by rules.ModuleFacts)."""
    out: List[Finding] = []
    taxonomy = native_bail_taxonomy(docs_obs_text)
    dyn_rows = {r.split("<")[0]: r for r in taxonomy if "<" in r}
    exact_rows = {r for r in taxonomy if "<" not in r}

    c_literals, c_prefixes = _collect_c_bails(all_cfacts)

    def covered(reason: str) -> bool:
        # exact rows ONLY: a literal reason in code is an exact member
        # of the registry. Dynamic rows (`op-<type>`) exist for the
        # snprintf/classifier-BUILT families and must not shadow the
        # exact namespace under their prefix — else a new `op-foo`
        # literal would ship undocumented and deleting the `op-shape`
        # row would go unnoticed.
        return reason in exact_rows

    if not taxonomy and (c_literals or py_bail_literals):
        first = c_literals[0] if c_literals else None
        out.append(Finding(
            "N4", first[0] if first else docs_obs_name,
            first[2] if first else 1, first[3] if first else "",
            "no 'Native bail taxonomy' table found in %s — the bail "
            "registry the C and Python gates classify into must be "
            "cataloged there" % docs_obs_name))
        return out

    seen: Set[str] = set()
    for (path, reason, line, func) in c_literals:
        if reason in seen:
            continue
        seen.add(reason)
        if not covered(reason):
            out.append(Finding(
                "N4", path, line, func,
                "C bail reason %r has no row in the %s native-bail "
                "taxonomy table" % (reason, docs_obs_name)))
    for prefix in sorted(c_prefixes):
        if prefix not in dyn_rows:
            # the snprintf family needs a dynamic `prefix<...>` row
            out.append(Finding(
                "N4", all_cfacts[0].path if all_cfacts else docs_obs_name,
                1, "",
                "dynamic C bail family %r (snprintf into bailbuf) has "
                "no `%s<...>` row in the %s taxonomy"
                % (prefix, prefix, docs_obs_name)))
    for (path, line, reason, qual) in py_bail_literals:
        if reason in seen:
            continue
        seen.add(reason)
        if not covered(reason):
            out.append(Finding(
                "N4", path, line, qual,
                "Python bail reason %r has no row in the %s "
                "native-bail taxonomy table" % (reason, docs_obs_name)))

    live = {r for (_p, r, _l, _f) in c_literals} | \
        {r for (_p, _l, r, _q) in py_bail_literals}
    for row in sorted(taxonomy):
        if "<" in row:
            # a dynamic row is kept alive by a dynamic PRODUCER (a
            # snprintf-into-bailbuf family) only — exact literals
            # under the prefix have their own rows
            if row.split("<")[0] not in c_prefixes:
                out.append(Finding(
                    "N4", docs_obs_name, 1, "",
                    "taxonomy row `%s` matches no dynamic bail "
                    "producer left in the tree — remove or fix it"
                    % row))
        elif row not in live:
            out.append(Finding(
                "N4", docs_obs_name, 1, "",
                "taxonomy row `%s` has no ctx_bail/env_bail/_bail call "
                "site left in the tree — remove or fix it" % row))

    if bail_test_text is not None and \
            "native_bail_taxonomy" not in bail_test_text:
        out.append(Finding(
            "N4", bail_test_name, 1, "",
            "%s no longer exercises the native-bail taxonomy "
            "(expected a native_bail_taxonomy() cross-check) — the "
            "registry, docs and test move together" % bail_test_name))

    # -- op-type table -------------------------------------------------------
    if op_type_names is not None:
        # the op table lives in ONE translation unit (the apply
        # engine): check the TU with the largest OP_* define set, so a
        # stray OP_-prefixed constant in another file can't demand all
        # 14 wire types there
        engine_facts: Optional[CFileFacts] = None
        engine_defs: Dict[int, str] = {}
        for facts in all_cfacts:
            defs: Dict[int, str] = {}
            for (name, val) in facts.defines.items():
                if name.startswith("OP_"):
                    try:
                        defs[int(val, 0)] = name
                    except ValueError:
                        continue
            if len(defs) > len(engine_defs):
                engine_facts, engine_defs = facts, defs
        if engine_facts is not None:
            for v, name in sorted(engine_defs.items()):
                if v not in op_type_names:
                    out.append(Finding(
                        "N4", engine_facts.path, 1, "",
                        "C op-type define %s=%d has no Python "
                        "OP_TYPE_NAMES entry — its op_stats row would "
                        "surface as `ledger.apply.op.unknown-%d`"
                        % (name, v, v)))
            for v, pyname in sorted(op_type_names.items()):
                if v not in engine_defs:
                    out.append(Finding(
                        "N4", engine_facts.path, 1, "",
                        "wire op type %d (`%s`) has no OP_* define in "
                        "%s — the engine cannot classify or attribute "
                        "it" % (v, pyname, engine_facts.path)))
            maxop = engine_facts.defines.get("MAX_OPTYPES")
            if maxop is not None:
                try:
                    if int(maxop.split()[0], 0) <= max(engine_defs):
                        out.append(Finding(
                            "N4", engine_facts.path, 1, "",
                            "MAX_OPTYPES (%s) does not cover the "
                            "largest OP_* define (%d) — the op_stats "
                            "table would drop its attribution"
                            % (maxop, max(engine_defs))))
                except ValueError:
                    pass
        if "ledger.apply.op.<" not in docs_metrics_text:
            out.append(Finding(
                "N4", docs_metrics_name, 1, "",
                "the dynamic `ledger.apply.op.<type>` prefix is no "
                "longer documented in %s — the C op_stats table feeds "
                "exactly that name space" % docs_metrics_name))
    return out
