"""Span tracer + flight recorder: the close path explains itself.

Role parity: the reference leans on medida timers plus hand-run `perf`
for latency attribution; DSig-style pipelines (PAPERS.md) show why a
replicated signature pipeline needs per-stage spans instead — the
headline numbers (batch-verify throughput, replay speedup) are only
auditable when every BENCH artifact carries a machine-generated phase
breakdown. This module provides:

- `Tracer`: nested spans with tags, recorded into a bounded ring buffer.
  Disabled (the default) it is one attribute check per span — cheap
  enough to leave the instrumentation permanently in the hot paths
  (tests/test_tracing.py pins the disabled-overhead guard).
- Chrome-trace-event export (`to_chrome_trace`) for chrome://tracing /
  Perfetto, served by the admin `trace` endpoint.
- `phase_breakdown`: exclusive (self-time) per-phase totals computed
  from real spans — what bench.py embeds in BENCH_*.json so device vs
  fallback verify attribution is structural, not prose.
- `FlightRecorder`: snapshots the last N spans + the metrics registry to
  a JSON file on unhandled close exceptions and on SCP-stall /
  slow-close watchdog triggers, so a wedged or stalled node leaves a
  black box behind instead of a mystery.

Threading: span stacks are thread-local (worker-thread dispatches nest
correctly); the ring buffer append is a deque op under a lock only on
the multi-producer paths' writes — GIL-atomic deque.append keeps the
single-threaded hot path lock-free.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, List, Optional

from .log import get_logger

log = get_logger("Perf")

DEFAULT_CAPACITY = 16384


class Span:
    """One completed (or in-flight) traced region."""

    __slots__ = ("name", "cat", "t0", "dur", "tags", "tid", "sid",
                 "parent", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 tags: Optional[dict]) -> None:
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.tags: Optional[dict] = tags
        self.tid = threading.get_ident()
        self.sid = 0
        self.parent = 0
        self.t0 = 0.0
        self.dur: Optional[float] = None   # None while open

    def set_tag(self, key: str, value) -> "Span":
        if self.tags is None:
            self.tags = {}
        self.tags[key] = value
        return self

    def __enter__(self) -> "Span":
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.set_tag("error", exc_type.__name__)
        self._tracer._pop(self)
        return False

    def to_dict(self) -> dict:
        d = {"name": self.name, "cat": self.cat, "ts": self.t0,
             "dur": self.dur, "tid": self.tid, "sid": self.sid,
             "parent": self.parent}
        if self.tags:
            d["tags"] = dict(self.tags)
        return d


class _NoopSpan:
    """Shared do-nothing span handed out while tracing is disabled."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_tag(self, key: str, value) -> "_NoopSpan":
        return self


_NOOP = _NoopSpan()


def tracer_span(tracer, name: str, cat: str = "core", **tags):
    """The single tracer-guard: a span against a possibly-absent,
    possibly-disabled tracer. Every instrumentation site goes through
    this (or the wrappers below) so the enable semantics live in one
    place."""
    if tracer is None or not tracer.enabled:
        return _NOOP
    return tracer.span(name, cat, **tags)


def tracer_instant(tracer, name: str, cat: str = "core", **tags) -> None:
    if tracer is not None and tracer.enabled:
        tracer.instant(name, cat, **tags)


def app_span(app, name: str, cat: str = "core", **tags):
    """Span against `app.tracer`, tolerating apps (test doubles, partial
    wirings) that have no tracer at all — the instrumentation sites must
    never require one."""
    return tracer_span(getattr(app, "tracer", None), name, cat, **tags)


class Tracer:
    """Bounded-ring span recorder; see module docstring."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 now_fn: Callable[[], float] = time.perf_counter) -> None:
        self.enabled = False
        self._now = now_fn
        self._buf: deque = deque(maxlen=capacity)
        self._tls = threading.local()
        self._next_sid = 0
        self._sid_lock = threading.Lock()
        self.dropped = 0   # spans evicted from the ring since enable()

    @property
    def capacity(self) -> int:
        return self._buf.maxlen or 0

    # -- lifecycle -----------------------------------------------------------
    def enable(self, capacity: Optional[int] = None) -> None:
        if capacity is not None and capacity != self._buf.maxlen:
            self._buf = deque(self._buf, maxlen=capacity)
        self.dropped = 0
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._buf.clear()
        self.dropped = 0

    # -- recording -----------------------------------------------------------
    def span(self, name: str, cat: str = "core", **tags):
        """`with tracer.span("close.apply", seq=7):` — returns a shared
        no-op when disabled; tag values must be JSON-serializable."""
        if not self.enabled:
            return _NOOP
        return Span(self, name, cat, tags or None)

    def instant(self, name: str, cat: str = "core", **tags) -> None:
        """Zero-duration marker event (Chrome 'i' phase)."""
        if not self.enabled:
            return
        s = Span(self, name, cat, tags or None)
        s.t0 = self._now()
        s.dur = 0.0
        s.sid = self._new_sid()
        s.parent = self._stack()[-1].sid if self._stack() else 0
        self._record(s)

    def _new_sid(self) -> int:
        with self._sid_lock:
            self._next_sid += 1
            return self._next_sid

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, span: Span) -> None:
        st = self._stack()
        span.sid = self._new_sid()
        span.parent = st[-1].sid if st else 0
        st.append(span)
        span.t0 = self._now()

    def _pop(self, span: Span) -> None:
        span.dur = self._now() - span.t0
        st = self._stack()
        # tolerate mismatched exits (a span leaked across an exception):
        # unwind to and including this span
        while st:
            top = st.pop()
            if top is span:
                break
        self._record(span)

    def _record(self, span: Span) -> None:
        if len(self._buf) == self._buf.maxlen:
            self.dropped += 1
        self._buf.append(span)

    # -- inspection ----------------------------------------------------------
    def spans(self, last_n: Optional[int] = None) -> List[Span]:
        out = list(self._buf)
        if last_n is not None:
            # guard last_n=0: out[-0:] would be the WHOLE list
            out = out[-last_n:] if last_n > 0 else []
        return out

    def open_spans(self) -> List[Span]:
        """In-flight spans on the CALLING thread (flight-recorder dumps
        run on the thread that hit the trigger, which is the interesting
        stack)."""
        return list(self._stack())

    def to_chrome_trace(self, last_n: Optional[int] = None) -> dict:
        """Chrome trace-event JSON (chrome://tracing, Perfetto): complete
        ('X') events with microsecond timestamps, tags under args."""
        events = []
        for s in self.spans(last_n):
            ev = {"name": s.name, "cat": s.cat,
                  "ph": "X" if s.dur else "i",
                  "ts": round(s.t0 * 1e6, 1),
                  "dur": round((s.dur or 0.0) * 1e6, 1),
                  "pid": os.getpid(), "tid": s.tid}
            if s.tags:
                ev["args"] = s.tags
            if ev["ph"] == "i":
                ev["s"] = "t"
                del ev["dur"]
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "dropped_spans": self.dropped}

    # -- phase attribution ---------------------------------------------------
    def phase_breakdown(self, wall_s: Optional[float] = None,
                        phase_of: Optional[Callable[[Span],
                                                    Optional[str]]] = None,
                        ) -> dict:
        """Exclusive per-phase totals from the recorded spans.

        Self-time = span duration minus its direct children's durations,
        so nested spans (verify drains inside an apply span) never double
        count. Default phase key is the span name with a `backend` tag
        appended (`crypto.verify_many:tpu` vs `:cpu`) — the device-vs-
        fallback attribution the r5 postmortem demanded. With `wall_s`,
        adds an `untraced` phase (wall minus the dominant thread's root
        spans) so the totals sum to the measured wall exactly on
        single-threaded runs; concurrent worker-thread spans (tpu-async
        dispatches) still report their own self-time, so accounted_s may
        legitimately exceed wall then.
        """
        spans = [s for s in self._buf if s.dur is not None]
        child_time: Dict[int, float] = {}
        for s in spans:
            if s.parent:
                child_time[s.parent] = child_time.get(s.parent, 0.0) + s.dur
        phases: Dict[str, dict] = {}
        root_by_tid: Dict[int, float] = {}
        for s in spans:
            if phase_of is not None:
                key = phase_of(s)
                if key is None:
                    continue
            else:
                key = s.name
                if s.tags and "backend" in s.tags:
                    key = "%s:%s" % (key, s.tags["backend"])
                    # actual backing platform when it differs from the
                    # configured backend — a jax-on-CPU "tpu" drain keys
                    # as crypto.verify_many:tpu@cpu, not as device time
                    plat = s.tags.get("platform")
                    if plat and plat != s.tags["backend"]:
                        key = "%s@%s" % (key, plat)
            self_s = max(0.0, s.dur - child_time.get(s.sid, 0.0))
            p = phases.setdefault(key, {"total_s": 0.0, "count": 0})
            p["total_s"] += self_s
            p["count"] += 1
            if not s.parent:
                root_by_tid[s.tid] = root_by_tid.get(s.tid, 0.0) + s.dur
        out = {"phases": phases, "dropped_spans": self.dropped}
        if wall_s:
            # wall is covered by the DOMINANT thread's roots (the main
            # loop); worker-thread roots run concurrently with it and
            # must not deflate `untraced` (an async-backend dispatch span
            # overlaps a close span — summing both would clamp untraced
            # to 0 and push pct_of_wall past 100)
            root_total = max(root_by_tid.values(), default=0.0)
            untraced = max(0.0, wall_s - root_total)
            phases["untraced"] = {"total_s": untraced, "count": 1}
            out["wall_s"] = wall_s
        total = sum(p["total_s"] for p in phases.values())
        out["accounted_s"] = round(total, 6)
        for p in phases.values():
            p["total_s"] = round(p["total_s"], 6)
            if wall_s:
                p["pct_of_wall"] = round(100.0 * p["total_s"] / wall_s, 2)
        return out


class FlightRecorder:
    """Black box: on a trigger, snapshot the tracer ring + open spans +
    metrics registry to
    `<dir>/sct-flight[-<node>]-<reason>-<t>-<seq>.json` (node name +
    zero-padded app-clock stamp + per-recorder sequence: concurrent
    multi-node chaos runs sharing a directory — and repeat dumps at an
    unchanged virtual clock — never overwrite each other's evidence).
    Dump failures are logged, never raised — the recorder must not turn
    a stall into a crash."""

    def __init__(self, tracer: Tracer, metrics=None,
                 out_dir: Optional[str] = None,
                 max_spans: int = 512,
                 min_interval_s: float = 60.0,
                 node_name: str = "",
                 now_fn: Optional[Callable[[], float]] = None) -> None:
        import tempfile
        self.tracer = tracer
        self.metrics = metrics
        self.out_dir = (out_dir or os.environ.get("SCT_FLIGHT_DIR")
                        or tempfile.gettempdir())
        # node name + app-clock stamp go into every dump filename so
        # concurrent multi-node chaos runs sharing one directory never
        # overwrite each other's incident evidence
        self.node_name = node_name
        self._now = now_fn or time.monotonic
        self.max_spans = max_spans
        # per-reason cooldown: a sustained burst of triggers (every slow
        # close in a slow patch) must not re-serialize the registry on
        # each close nor overwrite the FIRST incident's evidence — the
        # first dump in a burst is the interesting one
        self.min_interval_s = min_interval_s
        self._last_dump_at: Dict[str, float] = {}
        self.dumps = 0
        self.suppressed = 0
        self.last_path: Optional[str] = None

    def dump(self, reason: str, exc: Optional[BaseException] = None,
             extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        try:
            now = time.monotonic()
            last = self._last_dump_at.get(reason)
            if not force and last is not None and \
                    now - last < self.min_interval_s:
                self.suppressed += 1
                return None
            self._last_dump_at[reason] = now
            blob = {
                "reason": reason,
                "at_unix": int(time.time()),
                "pid": os.getpid(),
                "spans": [s.to_dict()
                          for s in self.tracer.spans(self.max_spans)],
                "open_spans": [s.to_dict()
                               for s in self.tracer.open_spans()],
                "dropped_spans": self.tracer.dropped,
                "tracing_enabled": self.tracer.enabled,
            }
            if exc is not None:
                blob["exception"] = {
                    "type": type(exc).__name__,
                    "message": str(exc),
                    "traceback": traceback.format_exception(
                        type(exc), exc, exc.__traceback__),
                }
            if self.metrics is not None:
                blob["metrics"] = self.metrics.to_json()
            if extra:
                blob["extra"] = extra
            def _safe(s: str) -> str:
                return "".join(c if c.isalnum() or c in "-_" else "-"
                               for c in s)
            parts = ["sct-flight"]
            if self.node_name:
                parts.append(_safe(self.node_name))
            parts.append(_safe(reason))
            # app-clock stamp + per-recorder sequence: two forced dumps
            # at an UNCHANGED virtual clock must still get distinct
            # paths, or the second overwrites the first's evidence
            parts.append("%012.3f" % max(0.0, self._now()))
            parts.append("%03d" % self.dumps)
            path = os.path.join(self.out_dir,
                                "-".join(parts) + ".json")
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(blob, fh, indent=1, default=repr)
            os.replace(tmp, path)
            self.dumps += 1
            self.last_path = path
            log.warning("flight recorder dumped %r to %s", reason, path)
            return path
        except Exception as e:   # noqa: BLE001 - recorder never raises
            log.error("flight recorder dump failed: %s", e)
            return None
