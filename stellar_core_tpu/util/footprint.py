"""Node footprint census (ISSUE 19 tentpole;
docs/observability.md#node-footprint).

ROADMAP item 2's open half says it outright: "if per-process overhead
blocks 100 nodes, refactor toward a lighter in-sim node" — a refactor
nobody can aim without a per-node resource census. This module is that
census, the measure-before-offload discipline DSig applies to
datacenter signature paths (PAPERS.md 2406.07215) turned on our own
node: every bounded structure in the process (hop rings, LRU caches,
ingress intake and source buckets, the tx-lifecycle tracker, slot
timelines, SCP per-slot state, peer send queues) registers with a
`BoundedStructRegistry` and self-reports occupancy / capacity /
approximate bytes, alongside process-level RSS, thread count and fd
count read from `/proc` (stdlib only — no psutil).

Registration discipline: `track_struct` call sites use LITERAL
structure names — sctlint's M1 scanner catalogs them exactly like
`new_*` metric registrations (as `footprint.struct.<name>` rows in
docs/metrics.md), so registering a structure without documenting it
fails the gate, the same drift guard the metric catalog has.

Consumers:

- admin `footprint` endpoint (`to_json`) — the per-node overhead table;
- the metrics registry (`footprint.*` names → `sct_footprint_*` in the
  Prometheus exposition);
- the fleet view: util/fleet.py merges per-node `fleet_json()` blobs
  into the fleet overhead table and the N-vs-RSS scaling curve
  `bench.py --fleet-scale` records (the committed baseline the
  lighter-in-sim-node refactor is gated against).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional

from .metrics import MetricsRegistry
from .threads import TrackedLock
from .timer import real_monotonic


def process_stats() -> dict:
    """Process-level footprint from /proc (Linux; ru_maxrss fallback):
    resident set in MB, live thread count, open fd count (-1 when
    /proc/self/fd is unreadable)."""
    rss_kb = 0
    try:
        with open("/proc/self/status", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    rss_kb = int(line.split()[1])
                    break
    except (OSError, ValueError, IndexError):
        try:
            import resource
            # ru_maxrss is the high-water mark, not current RSS — an
            # over-estimate is still a usable scaling signal
            rss_kb = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        except (ImportError, OSError, ValueError):
            rss_kb = 0
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        fds = -1
    return {"rss_mb": round(rss_kb / 1024.0, 3),
            "threads": threading.active_count(),
            "fds": fds}


class BoundedStructRegistry:
    """The census: named bounded structures self-report occupancy /
    capacity / approx bytes through registered callables; `census()`
    snapshots them all plus the process stats. A structure whose
    callbacks raise (owner torn down mid-run) reports an `error` field
    instead of killing the census."""

    MAX_STRUCTS = 256   # registrations retained (the census's own bound)

    def __init__(self, metrics=None, now_fn=None,
                 node_name: str = "") -> None:
        self._now = now_fn or real_monotonic
        # a private registry when none is injected keeps direct
        # constructions (tests, harnesses) app-registry-free while
        # letting every registration below use the new_* idiom the M1
        # metric-catalog scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.node_name = node_name
        self._lock = TrackedLock("util.footprint")
        m = self.metrics
        self._g_structs = m.new_gauge("footprint.structs")
        self._g_rss = m.new_gauge("footprint.rss-mb")
        self._g_threads = m.new_gauge("footprint.threads")
        self._g_fds = m.new_gauge("footprint.fds")
        self._g_occ: Dict[str, object] = {}
        self._structs: Dict[str, dict] = {}
        self.dropped_registrations = 0

    # -- registration --------------------------------------------------------
    def track_struct(self, name: str, kind: str,
                     capacity_fn: Callable[[], int],
                     occupancy_fn: Callable[[], int],
                     bytes_fn: Optional[Callable[[], int]] = None) -> bool:
        """Register one bounded structure. Call sites pass a LITERAL
        `name` — the M1 scanner catalogs it as `footprint.struct.<name>`
        against docs/metrics.md. Re-registering a name replaces the
        callbacks (a node restart re-wires the same structures).
        Returns False past MAX_STRUCTS (the census stays bounded)."""
        with self._lock:
            if name not in self._structs and \
                    len(self._structs) >= self.MAX_STRUCTS:
                self.dropped_registrations += 1
                return False
            self._structs[name] = {"kind": kind, "capacity": capacity_fn,
                                   "occupancy": occupancy_fn,
                                   "bytes": bytes_fn}
            if name not in self._g_occ:
                self._g_occ[name] = self.metrics.new_gauge(
                    "footprint.struct.%s" % name)
            self._g_structs.set(len(self._structs))
        return True

    # -- census --------------------------------------------------------------
    def census(self) -> dict:
        """Snapshot every registered structure + the process stats.
        `over_capacity` lists structures whose occupancy exceeds their
        own declared cap — always empty unless a bound is broken (the
        footprint soak test and validate_footprint assert exactly
        that)."""
        with self._lock:
            items = list(self._structs.items())
        structs: Dict[str, dict] = {}
        over = []
        approx_total = 0
        for name, fns in items:
            entry: dict = {"kind": fns["kind"]}
            try:
                occ = int(fns["occupancy"]())
                cap = int(fns["capacity"]())
                entry["occupancy"] = occ
                entry["capacity"] = cap
                if fns["bytes"] is not None:
                    b = int(fns["bytes"]())
                    entry["approx_bytes"] = b
                    approx_total += b
                if 0 <= cap < occ:
                    over.append(name)
            except Exception as e:
                # the owner may have been torn down (node stop in a
                # simulation) — report, don't crash the census
                entry["error"] = repr(e)
            structs[name] = entry
            g = self._g_occ.get(name)
            if g is not None and "occupancy" in entry:
                g.set(entry["occupancy"])
        proc = process_stats()
        self._g_rss.set(proc["rss_mb"])
        self._g_threads.set(proc["threads"])
        self._g_fds.set(max(0, proc["fds"]))
        return {"structs": structs, "process": proc,
                "over_capacity": over,
                "approx_bytes_total": approx_total,
                "dropped_registrations": self.dropped_registrations}

    # -- exports -------------------------------------------------------------
    def to_json(self) -> dict:
        """The admin `footprint` blob — one node's overhead table."""
        return {"node": self.node_name, **self.census()}

    def fleet_json(self) -> dict:
        """Compact per-node export FleetAggregator merges into the
        fleet overhead table (one shape for in-process `add_app` and
        HTTP `add_http` intake — identical to `to_json` by design)."""
        return self.to_json()
