"""SlotTimeline: per-slot consensus event journal.

Role parity: the reference answers "where did slot N spend its time"
with per-node medida timers plus operator folklore; committee-consensus
measurement work (arXiv:2302.00418, DSig in PAPERS.md) shows commit
latency is dominated by cross-node propagation and stragglers — a
dimension a per-node span ring cannot see. This module records, for
every slot, the consensus-visible moments (first nomination vote seen,
own vote, accepts, ballot phase transitions, externalize, txset fetch,
ledger apply), each stamped with:

- `t`  — the application clock (virtual in tests/simulation, monotonic
  live), the per-node causal order;
- `pc` — `time.perf_counter()`, shared by every node in one process, so
  the fleet aggregator (util/fleet.py) can align N simulated nodes on
  one axis and compute externalize skew / flood latency across them.

Events carrying a `node` name the *sending* node (hex node id) — the
raw material for flood-latency and straggler attribution.

The journal is always on (unlike the span tracer): one dict append per
event, bounded by `max_slots` slots x `max_events_per_slot` events, with
per-slot (event, node) dedup for the seen-from-peer sites so a chatty
peer can't grow a slot's journal past nodes x statement-types.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Set, Tuple

DEFAULT_MAX_SLOTS = 64
DEFAULT_MAX_EVENTS = 512


class SlotTimeline:
    def __init__(self, now_fn: Optional[Callable[[], float]] = None,
                 max_slots: int = DEFAULT_MAX_SLOTS,
                 max_events_per_slot: int = DEFAULT_MAX_EVENTS) -> None:
        self._now = now_fn or time.monotonic
        self.max_slots = max_slots
        self.max_events_per_slot = max_events_per_slot
        self._slots: "OrderedDict[int, List[dict]]" = OrderedDict()
        self._seen: Dict[int, Set[Tuple[str, Optional[str]]]] = {}
        self.dropped_slots = 0    # slots evicted from the ring
        self.dropped_events = 0   # events refused (stale slot / full slot)

    # -- recording -----------------------------------------------------------
    def record(self, slot: int, event: str,
               node: Optional[str] = None, dedupe: bool = False,
               dedupe_key: Optional[str] = None,
               **tags) -> bool:
        """Append one event to `slot`'s journal. With dedupe=True, only
        the FIRST (event, node) pair per slot is kept — the envelope-seen
        sites use this so the journal records first-arrival times, not
        every duplicate flood copy. `dedupe_key` replaces `node` in the
        dedup identity for events whose distinguishing dimension isn't
        the sender (competing txsets for one slot keyed by hash).
        Returns False when the event was dropped (deduped, slot evicted,
        or journal full)."""
        evs = self._slots.get(slot)
        if evs is None:
            if len(self._slots) >= self.max_slots:
                oldest = min(self._slots)
                if slot < oldest:
                    # a straggler event for an already-evicted slot must
                    # not resurrect it (the ring tracks RECENT slots)
                    self.dropped_events += 1
                    return False
                del self._slots[oldest]
                self._seen.pop(oldest, None)
                self.dropped_slots += 1
            evs = self._slots[slot] = []
        if dedupe:
            seen = self._seen.setdefault(slot, set())
            key = (event, dedupe_key if dedupe_key is not None else node)
            if key in seen:
                self.dropped_events += 1
                return False
            seen.add(key)
        if len(evs) >= self.max_events_per_slot:
            self.dropped_events += 1
            return False
        ev = {"event": event, "t": round(self._now(), 6),
              "pc": time.perf_counter()}
        if node is not None:
            ev["node"] = node
        if tags:
            ev.update(tags)
        evs.append(ev)
        return True

    # -- inspection ----------------------------------------------------------
    def slots(self) -> List[int]:
        return sorted(self._slots)

    def events(self, slot: int) -> List[dict]:
        # copies, not aliases: consumers (the fleet aggregator rebases
        # pc stamps in place) must not corrupt the live journal
        return [dict(ev) for ev in self._slots.get(slot, ())]

    def first(self, slot: int, event: str) -> Optional[dict]:
        for ev in self._slots.get(slot, ()):
            if ev["event"] == event:
                return ev
        return None

    def to_json(self, slot: Optional[int] = None) -> dict:
        """One slot's journal (`slot=N`) or the whole ring. The admin
        `timeline` endpoint and the fleet aggregator both consume this
        schema: {slots: {"<idx>": [event...]}, dropped_*}."""
        if slot is not None:
            slots = {str(slot): self.events(slot)}
        else:
            slots = {str(i): [dict(ev) for ev in evs]
                     for i, evs in sorted(self._slots.items())}
        return {"slots": slots,
                "dropped_slots": self.dropped_slots,
                "dropped_events": self.dropped_events}

    def clear(self) -> None:
        self._slots.clear()
        self._seen.clear()
        self.dropped_slots = 0
        self.dropped_events = 0
