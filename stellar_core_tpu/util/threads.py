"""Thread-discipline runtime checks: affinity assertions and a debug
lock-order checker.

The framework's determinism story (per-height header-hash equality,
virtual-clock replay, seeded chaos soaks) rests on the single-threaded
consensus contract (docs/architecture.md:23-26): ledger state is only
ever mutated from the thread that cranks the VirtualClock; worker
threads (verify dispatch, quorum-intersection, the TCP reactor, HTTP
handlers) post completions back via `post_to_main`. The reference
encodes this as `threadIsMain()` release-asserts throughout
stellar-core; this module is that runtime twin, paired with the static
T1 rule in `stellar_core_tpu/analysis` (docs/static-analysis.md).

Contract mirrors the tracer's: everything here is a near-no-op until
armed. Tests arm it for the whole tier-1 run (tests/conftest.py);
production can opt in with SCT_THREAD_CHECKS=1.

- `@main_thread_only` marks a mutation entry point: registers its
  qualname (the static T1 call-graph walk reads the same registry
  semantics from source) and, when armed, release-asserts the caller is
  the bound main thread.
- `assert_main_thread(what)` is the inline form for code that cannot
  take a decorator (C-extension call sites, properties).
- `TrackedLock` wraps `threading.Lock` with acquisition-order tracking:
  the process-wide order graph gains an edge A->B the first time a
  thread acquires B while holding A; an edge that closes a cycle raises
  `LockOrderError` carrying BOTH acquisition stacks (the recorded one
  that created the conflicting edge and the current one).
- `WORKER_THREAD_REGISTRY` + `spawn_worker(name, target)`: every
  long-lived worker the framework starts (verify dispatch, verify
  staging, kernel warmup, quorum-intersection, ...) is spawned through
  one audited factory under a registered name, so the set of threads
  that may exist is a reviewable registry instead of grep output — and
  the static T1 rule follows `spawn_worker` targets exactly like bare
  `Thread(target=...)` sites (docs/static-analysis.md).
"""

from __future__ import annotations

import functools
import threading
import traceback
from typing import Callable, Dict, List, Optional, Set

from .log import get_logger

log = get_logger("Fs")

_armed = False
_main_thread: Optional[threading.Thread] = None

# qualname -> module of every @main_thread_only function; the static T1
# rule and tests/test_threads.py assert this registry covers the hot
# mutation points
MAIN_THREAD_REGISTRY: Dict[str, str] = {}

# name -> description of every worker thread the framework may start —
# long-lived workers (verify dispatch, warmup) and short-lived per-job
# ones (a staging job per drain chunk) alike. Spawning through
# `spawn_worker` asserts membership, so a new thread cannot appear
# without a registry entry (and the matching module-docstring audit
# line); tests/test_threads.py pins the set.
WORKER_THREAD_REGISTRY: Dict[str, str] = {
    "crypto.verify-dispatch":
        "ThreadedBatchVerifier batch dispatch; completes futures via "
        "clock.post_to_main only",
    "crypto.verify-staging":
        "TpuSigVerifier double-buffer staging: packs + device_puts "
        "drain chunk K+1 while the device runs chunk K (one short-"
        "lived job thread per staged chunk — spawn cost is microseconds "
        "against a multi-second device dispatch)",
    "crypto.verify-warmup":
        "TpuSigVerifier AOT bucket warmup; touches JAX state only",
    "crypto.hash-staging":
        "TpuBatchHasher double-buffer staging: FIPS-pads + device_puts "
        "hash chunk K+1 while the device digests chunk K (one short-"
        "lived job thread per staged chunk, mirroring verify staging)",
    "crypto.hash-warmup":
        "TpuBatchHasher AOT shape warmup; touches JAX state only",
    "catchup.prewarm-pipeline":
        "Pipelined catchup (ISSUE 13): verifies ledger N+1's signature "
        "triples (verifier.prewarm_many — pure crypto, GIL-releasing) "
        "while the main thread applies ledger N; triples are collected "
        "on the MAIN thread (no cross-thread ledger reads)",
    "crypto.cpu-verify-shard":
        "CPU verify sharding (crypto/keys.raw_verify_batch): one chunk "
        "of a large ed25519 batch per thread through the native "
        "verify_batch ctypes call (GIL released inside the call)",
}


def register_worker_thread(name: str, description: str) -> None:
    """Register an additional worker-thread entry point (subsystems
    outside crypto add theirs at import time)."""
    WORKER_THREAD_REGISTRY[name] = description


def spawn_worker(name: str, target: Callable[[], None],
                 daemon: bool = True) -> threading.Thread:
    """Start a named worker thread; `name` must be registered in
    WORKER_THREAD_REGISTRY (an unregistered spawn is a programming
    error, caught in tier-1 — not an operator-facing failure)."""
    assert name in WORKER_THREAD_REGISTRY, (
        "worker thread %r is not in util.threads.WORKER_THREAD_REGISTRY "
        "— register it (with a description) before spawning" % name)
    t = threading.Thread(target=target, name=name, daemon=daemon)
    t.start()
    return t


class ThreadDisciplineError(AssertionError):
    """A worker thread called a main-thread-only entry point."""


class LockOrderError(AssertionError):
    """Two locks were acquired in both orders somewhere in the process:
    a latent deadlock even if the two threads never actually race."""


def arm(main_thread: Optional[threading.Thread] = None) -> None:
    """Enable affinity + lock-order checks; binds `main_thread` (default:
    the calling thread) as THE consensus thread. Re-arming rebinds."""
    global _armed, _main_thread
    _main_thread = main_thread or threading.current_thread()
    _armed = True


def disarm() -> None:
    global _armed, _main_thread
    _armed = False
    _main_thread = None
    _lock_order.reset()


def is_armed() -> bool:
    return _armed


def bound_main_thread() -> Optional[threading.Thread]:
    return _main_thread


def is_main_thread() -> bool:
    return threading.current_thread() is (_main_thread or
                                          threading.main_thread())


def assert_main_thread(what: str = "") -> None:
    """Release-assert the caller is the bound main thread (no-op until
    armed). Mirrors reference `releaseAssert(threadIsMain())`."""
    if not _armed:
        return
    cur = threading.current_thread()
    if cur is not _main_thread:
        raise ThreadDisciplineError(
            "%s called from thread %r; ledger/consensus state may only "
            "be touched from the main thread %r (use clock.post_to_main)"
            % (what or "main-thread-only code", cur.name,
               _main_thread.name if _main_thread else "<unbound>"))


def main_thread_only(fn: Callable) -> Callable:
    """Mark + guard a consensus/ledger mutation entry point."""
    MAIN_THREAD_REGISTRY[fn.__qualname__] = fn.__module__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _armed and threading.current_thread() is not _main_thread:
            assert_main_thread(fn.__qualname__)
        return fn(*args, **kwargs)

    wrapper.__sct_main_thread_only__ = True
    return wrapper


# --------------------------------------------------------------------------
# Lock-order checker


class _LockOrderGraph:
    """Process-wide acquisition-order graph over TrackedLock names.

    Nodes are lock names; a directed edge A->B means "some thread
    acquired B while holding A". The first acquisition that would make
    B reach A (a cycle) raises. Stacks are only captured when an edge is
    first added, so steady-state tracked acquires cost two dict hits.
    """

    def __init__(self) -> None:
        self._mutex = threading.Lock()   # guards the graph itself
        self._edges: Dict[str, Set[str]] = {}
        self._edge_stacks: Dict[tuple, str] = {}
        self._held = threading.local()   # per-thread stack of lock names

    def reset(self) -> None:
        with self._mutex:
            self._edges.clear()
            self._edge_stacks.clear()

    def _holding(self) -> List[str]:
        h = getattr(self._held, "stack", None)
        if h is None:
            h = self._held.stack = []
        return h

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """Shortest established-order path src -> ... -> dst, or None."""
        seen = {src}
        frontier: List[List[str]] = [[src]]
        while frontier:
            path = frontier.pop(0)
            n = path[-1]
            if n == dst:
                return path
            for nxt in sorted(self._edges.get(n, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(path + [nxt])
        return None

    def note_acquire(self, name: str) -> None:
        held = self._holding()
        if held:
            prev = held[-1]
            if prev != name:
                with self._mutex:
                    out = self._edges.setdefault(prev, set())
                    if name not in out:
                        # new edge: cycle-check before committing it
                        path = self._find_path(name, prev)
                        if path is not None:
                            here = "".join(traceback.format_stack(limit=16))
                            msg = [
                                "lock-order inversion: acquiring %r while "
                                "holding %r, but the order %s was already "
                                "established."
                                % (name, prev, " -> ".join(path)),
                                "--- current acquisition (%r after %r) ---"
                                % (name, prev), here]
                            # each established hop's recorded stack —
                            # for a 2-cycle that is THE conflicting
                            # acquisition; for longer cycles every link
                            # that closes the loop
                            for a, b in zip(path, path[1:]):
                                msg.append(
                                    "--- established order (%r after %r) "
                                    "recorded at ---" % (b, a))
                                msg.append(self._edge_stacks.get(
                                    (a, b),
                                    "<stack unavailable>"))
                            raise LockOrderError("\n".join(msg))
                        out.add(name)
                        self._edge_stacks[(prev, name)] = "".join(
                            traceback.format_stack(limit=16))
        held.append(name)

    def note_release(self, name: str) -> None:
        held = self._holding()
        # release order need not be LIFO; drop the most recent entry
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break


_lock_order = _LockOrderGraph()


def lock_order_graph() -> _LockOrderGraph:
    return _lock_order


class TrackedLock:
    """`threading.Lock` with optional acquisition-order tracking.

    Disarmed cost is one module-global bool check on top of the raw lock
    (the overhead-guard test in tests/test_threads.py keeps it honest),
    so hot locks — the verify cache, the threaded verifier's pending
    queue, the TCP reactor — can stay tracked permanently.
    """

    __slots__ = ("name", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _armed:
            _lock_order.note_acquire(self.name)
            try:
                got = self._lock.acquire(blocking, timeout)
            except BaseException:
                _lock_order.note_release(self.name)
                raise
            if not got:
                _lock_order.note_release(self.name)
            return got
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        self._lock.release()
        # unconditional (not gated on _armed): a disarm between an armed
        # acquire and this release must not leak a stale held-stack
        # entry into the thread's local state; with an empty stack this
        # is one getattr + an empty loop
        _lock_order.note_release(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


# production opt-in: the checks are process-lifetime cheap, but default
# off so a bare library import stays side-effect-free
import os as _os  # noqa: E402

if _os.environ.get("SCT_THREAD_CHECKS") == "1":
    arm(threading.main_thread())
