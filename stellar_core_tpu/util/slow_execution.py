"""LogSlowExecution: warn when a scoped operation overruns its budget.

Role parity: reference `src/util/LogSlowExecution.h` — a scope timer
that logs on destruction when elapsed time exceeds a threshold, used by
`LedgerManagerImpl::closeLedger` (:526-528) so operators see slow closes
in the "Perf" partition without tracing.
"""

from __future__ import annotations

import time

from .log import get_logger

log = get_logger("Perf")

DEFAULT_THRESHOLD = 1.0  # seconds (reference default: 1s)


class LogSlowExecution:
    """Context manager: `with LogSlowExecution("ledger close"):` logs a
    warning if the body takes longer than `threshold` seconds.

    `on_slow(elapsed)` fires on overrun after the log line — the
    slow-close watchdog hook the flight recorder hangs off (a close that
    blows its budget leaves a span+metrics snapshot behind). It must not
    raise into the traced scope."""

    def __init__(self, name: str,
                 threshold: float = DEFAULT_THRESHOLD,
                 on_slow=None) -> None:
        self.name = name
        self.threshold = threshold
        self.elapsed = 0.0
        self.on_slow = on_slow

    def __enter__(self) -> "LogSlowExecution":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.elapsed = time.perf_counter() - self._t0
        if self.elapsed > self.threshold:
            log.warning("%s hung for %.3fs (threshold %.1fs)",
                        self.name, self.elapsed, self.threshold)
            if self.on_slow is not None:
                try:
                    self.on_slow(self.elapsed)
                except Exception as e:   # noqa: BLE001
                    log.error("slow-execution hook failed: %s", e)
        return False
