"""Temporary directory management (reference `src/util/TmpDir.{h,cpp}`)."""

from __future__ import annotations

import os
import shutil
import tempfile


class TmpDir:
    def __init__(self, prefix: str = "sct", root: str | None = None) -> None:
        if root:
            os.makedirs(root, exist_ok=True)
        self.path = tempfile.mkdtemp(prefix=prefix + "-", dir=root)

    def join(self, *parts: str) -> str:
        return os.path.join(self.path, *parts)

    def remove(self) -> None:
        shutil.rmtree(self.path, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove()
        return False


class TmpDirManager:
    """Owns a root dir of tmpdirs, cleaned on startup (reference
    TmpDirManager role)."""

    def __init__(self, root: str) -> None:
        self.root = root
        shutil.rmtree(root, ignore_errors=True)
        os.makedirs(root, exist_ok=True)

    def tmp_dir(self, prefix: str) -> TmpDir:
        return TmpDir(prefix=prefix, root=self.root)

    def clean(self) -> None:
        shutil.rmtree(self.root, ignore_errors=True)
