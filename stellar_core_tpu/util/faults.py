"""Deterministic fault injection: named fault points with seeded
per-site schedules.

Role parity: the reference sneaks fault knobs into individual classes
(LoopbackPeer drop/damage probabilities, `ARTIFICIALLY_*` config flags);
DSig-style offload pipelines (PAPERS.md, arXiv:2406.07215) treat verifier
failure and degraded operation as first-class operating modes instead.
This module is the one registry every failure domain pulls from:

- `FaultInjector`: named fault points ("device.dispatch",
  "overlay.drop", "archive.corrupt", ...) each with an independent
  seeded RNG and a schedule (probability, max fire count, skip-first-N),
  so a chaos run replays identically from its seed.
- Every injection is counted in metrics (`fault.injected.<site>`) and
  tagged on the active span + emitted as a tracer instant, so a flight
  dump from a chaos run shows exactly which faults fired where.
- Configured from Config.FAULTS (TOML table), the `SCT_FAULTS` env spec,
  or at runtime via the admin `faults?action=...` endpoint
  (docs/robustness.md catalogs the sites and knobs).

`should_fire(site)` on an unconfigured site is one dict miss — cheap
enough to leave the check permanently on hot paths, the same contract
the tracer makes for disabled spans.
"""

from __future__ import annotations

import random
from typing import Dict, Optional

from .log import get_logger

log = get_logger("Fault")

# The F1 site registry: every `should_fire`/`fire_point` literal in the
# tree must be listed here, and every entry here must be cataloged in
# docs/robustness.md — both directions enforced by the F1 static rule
# (stellar_core_tpu/analysis, tests/test_static_analysis.py). The admin
# `faults?action=set` endpoint validates against this set, so a typo'd
# site name is a 400, not a silently-armed no-op.
KNOWN_SITES = frozenset({
    "device.dispatch",
    "verify.device-lost",
    "verify.staging-stall",
    "hash.device-lost",
    "hash.dispatch-fail",
    "commitment.sign-fail",
    "overlay.drop",
    "overlay.delay",
    "overlay.duplicate",
    "overlay.reorder",
    "overlay.flood-limit",
    "overlay.send-overflow",
    "archive.get-fail",
    "archive.corrupt",
    "archive.short-read",
    "apply.cluster-fail",
    "apply.pipeline-stall",
    "bucketdb.index-corrupt",
    "bucketdb.read-fail",
    "ingress.admit-stall",
    "ingress.shed-storm",
})


class InjectedFault(Exception):
    """Raised by call sites that turn a fired fault point into an
    exception (e.g. the device-dispatch site in the batch verifier)."""


class FaultSite:
    """Schedule for one named fault point."""

    __slots__ = ("name", "probability", "remaining", "skip", "rng",
                 "fired", "evaluated")

    def __init__(self, name: str, probability: float = 1.0,
                 count: Optional[int] = None, after: int = 0,
                 seed: int = 0) -> None:
        self.name = name
        self.probability = probability
        self.remaining = count          # None = unlimited
        self.skip = after               # evaluations to pass through first
        # per-site stream: adding/removing one site never shifts another
        # site's schedule (str seeding is stable across processes)
        self.rng = random.Random("%d:%s" % (seed, name))
        self.fired = 0
        self.evaluated = 0

    def to_json(self) -> dict:
        return {"probability": self.probability,
                "remaining": self.remaining, "skip": self.skip,
                "fired": self.fired, "evaluated": self.evaluated}


class FaultInjector:
    """Registry of fault points; see module docstring."""

    def __init__(self, seed: int = 0, metrics=None, tracer=None) -> None:
        self.seed = seed
        self.metrics = metrics
        self.tracer = tracer
        self._sites: Dict[str, FaultSite] = {}

    # -- configuration -------------------------------------------------------
    def configure(self, name: str, probability: float = 1.0,
                  count: Optional[int] = None, after: int = 0) -> FaultSite:
        if name not in KNOWN_SITES:
            # warn, don't raise: tests arm synthetic sites on purpose;
            # the operator-facing paths (admin endpoint, SCT_FAULTS env
            # spec) validate strictly before reaching here
            log.warning("arming fault site %r not in the F1 registry "
                        "(util.faults.KNOWN_SITES) — no code checks it, "
                        "so it will never fire", name)
        site = FaultSite(name, probability, count, after, seed=self.seed)
        self._sites[name] = site
        log.info("fault point %s armed: p=%g count=%s after=%d",
                 name, probability, count, after)
        return site

    def configure_from_spec(self, spec: str) -> None:
        """Parse `site:p=0.5,n=3,after=2;site2` (missing fields default to
        p=1, unlimited, no skip) — the SCT_FAULTS env format. Operator
        input: unknown site names raise, so a typo'd chaos run dies at
        startup instead of soaking fault-free."""
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, argstr = part.partition(":")
            if name.strip() not in KNOWN_SITES:
                raise ValueError(
                    "unknown fault site %r in SCT_FAULTS spec; known "
                    "sites: %s" % (name.strip(),
                                   ", ".join(sorted(KNOWN_SITES))))
            kwargs: dict = {}
            for kv in argstr.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                k = k.strip()
                if k in ("p", "probability"):
                    kwargs["probability"] = float(v)
                elif k in ("n", "count"):
                    kwargs["count"] = int(v)
                elif k == "after":
                    kwargs["after"] = int(v)
                else:
                    raise ValueError("unknown fault arg %r in %r" % (k, part))
            self.configure(name.strip(), **kwargs)

    def clear(self, name: Optional[str] = None) -> None:
        if name is None:
            self._sites.clear()
        else:
            self._sites.pop(name, None)

    def configured(self) -> bool:
        return bool(self._sites)

    # -- the hot check -------------------------------------------------------
    def should_fire(self, name: str) -> bool:
        site = self._sites.get(name)
        if site is None:
            return False
        site.evaluated += 1
        if site.skip > 0:
            site.skip -= 1
            return False
        if site.remaining is not None and site.remaining <= 0:
            return False
        if site.probability < 1.0 and site.rng.random() >= site.probability:
            return False
        if site.remaining is not None:
            site.remaining -= 1
        site.fired += 1
        self._mark(site)
        return True

    def fire_point(self, name: str) -> None:
        """`should_fire` + raise: for sites whose effect is an exception."""
        if self.should_fire(name):
            raise InjectedFault(name)

    def _mark(self, site: FaultSite) -> None:
        if self.metrics is not None:
            self.metrics.new_meter("fault.injected.%s" % site.name).mark()
        t = self.tracer
        if t is not None and t.enabled:
            # tag the innermost open span (the operation the fault landed
            # in) and drop an instant so the timeline shows the injection
            stack = t._stack()
            if stack:
                stack[-1].set_tag("fault", site.name)
            t.instant("fault.%s" % site.name, cat="fault",
                      fired=site.fired)

    # -- introspection -------------------------------------------------------
    def to_json(self) -> dict:
        return {"seed": self.seed,
                "sites": {n: s.to_json()
                          for n, s in sorted(self._sites.items())}}


def check_faults(owner, name: str) -> bool:
    """`should_fire` against an `owner.faults` that may be absent or None
    — call sites (verifier, transports, works) must never require an
    injector, mirroring tracing.app_span's contract."""
    f = getattr(owner, "faults", None)
    return f is not None and f.should_fire(name)
