"""Global deterministic RNG, reseedable per test.

Role parity: reference `src/util/Math.cpp` gRandomEngine + the Catch listener
that reseeds before every test case (src/test/test.cpp:47-68).
"""

from __future__ import annotations

import random

g_random = random.Random(0)


def reseed(seed: int) -> None:
    g_random.seed(seed)


def rand_int(lo: int, hi: int) -> int:
    """Uniform in [lo, hi]."""
    return g_random.randint(lo, hi)


def rand_fraction() -> float:
    return g_random.random()


def rand_flip() -> bool:
    return g_random.random() < 0.5


def rand_bytes(n: int) -> bytes:
    return bytes(g_random.getrandbits(8) for _ in range(n))


def rand_element(seq):
    return seq[g_random.randrange(len(seq))]
