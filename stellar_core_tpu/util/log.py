"""Partitioned logging.

Role parity: reference `src/util/Logging.h:25-36` (easylogging++ behind a
Logging facade with per-partition levels, runtime settable via HTTP `ll`).
"""

from __future__ import annotations

import logging
import sys

PARTITIONS = [
    "Fs", "SCP", "Bucket", "Database", "History", "Process", "Ledger",
    "Overlay", "Herder", "Tx", "LoadGen", "Work", "Invariant", "Perf",
    "Fault",
]

_FMT = "%(asctime)s [%(name)s %(levelname)s] %(message)s"
_initialized = False


def init_logging(level: int = logging.INFO) -> None:
    global _initialized
    if _initialized:
        return
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(_FMT))
    root = logging.getLogger("stellar")
    root.addHandler(h)
    root.setLevel(level)
    root.propagate = False
    _initialized = True


def get_logger(partition: str) -> logging.Logger:
    init_logging()
    assert partition in PARTITIONS, partition
    return logging.getLogger("stellar.%s" % partition)


_LEVELS = {
    "trace": logging.DEBUG, "debug": logging.DEBUG, "info": logging.INFO,
    "warning": logging.WARNING, "error": logging.ERROR, "fatal": logging.CRITICAL,
    "none": logging.CRITICAL + 10,
}


def set_log_level(partition: str | None, level_name: str) -> None:
    """Runtime log-level control (HTTP `ll` command parity)."""
    lv = _LEVELS[level_name.lower()]
    if partition is None:
        logging.getLogger("stellar").setLevel(lv)
    else:
        get_logger(partition).setLevel(lv)


def get_log_levels() -> dict:
    out = {}
    for p in PARTITIONS:
        lg = logging.getLogger("stellar.%s" % p)
        out[p] = logging.getLevelName(lg.getEffectiveLevel())
    return out
