"""XDR record streams: length-delimited XDR objects in a file.

Role parity: reference `src/util/XDRStream.h` (XDRInputFileStream /
XDROutputFileStream) used for history checkpoint files. Framing matches the
RFC 5531 record mark the reference uses: 4-byte big-endian length with the
high bit set (single-fragment records).
"""

from __future__ import annotations

import struct
from typing import Any, Iterator, Optional

_MARK = struct.Struct(">I")
_LAST_FRAG = 0x80000000


def frame_record(body: bytes) -> bytes:
    """One framed record: RFC 5531 mark + body. THE single definition
    of the framing rule — the file writer below and the bucket layer's
    hash/persist path (bucket.entry_record) both call it, so the bucket
    identity hash can never desynchronize from the read path's framing."""
    return _MARK.pack(len(body) | _LAST_FRAG) + body


class XDROutputFileStream:
    def __init__(self, path: str) -> None:
        self._f = open(path, "wb")

    def write_one(self, xdr_type: Any, value: Any) -> None:
        from ..xdr.codec import xdr_bytes
        body = xdr_bytes(xdr_type, value) if not hasattr(value, "to_xdr") \
            else value.to_xdr()
        self._f.write(frame_record(body))

    def write_record(self, record: bytes) -> None:
        """Write an already-framed record (RFC 5531 mark + XDR body):
        the bucket layer hashes and persists the SAME serialized bytes
        (bucket.entry_record — memoized per immutable entry), so a
        bucket file write never re-serializes what its hash already
        paid for."""
        self._f.write(record)

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class XDRInputFileStream:
    def __init__(self, path: str) -> None:
        self._f = open(path, "rb")

    def read_one(self, xdr_type: Any) -> Optional[Any]:
        hdr = self._f.read(4)
        if not hdr:
            return None
        if len(hdr) < 4:
            raise IOError("truncated record mark")
        n = _MARK.unpack(hdr)[0]
        if not (n & _LAST_FRAG):
            raise IOError("multi-fragment records unsupported")
        n &= ~_LAST_FRAG
        body = self._f.read(n)
        if len(body) < n:
            raise IOError("truncated record body")
        from ..xdr.codec import xdr_from
        return xdr_from(xdr_type, body)

    def read_all(self, xdr_type: Any) -> Iterator[Any]:
        while True:
            v = self.read_one(xdr_type)
            if v is None:
                return
            yield v

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
