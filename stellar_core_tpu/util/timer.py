"""VirtualClock / VirtualTimer: the event loop every subsystem runs on.

Role parity: reference `src/util/Timer.h:59,244` — a clock that either tracks
real time or fully virtual deterministic time (used by tests/simulation), an
event queue cranked from the main thread, and cancellable timers.

All consensus-touching work runs on the thread that cranks the clock
(reference threading contract, docs/architecture.md:23-26). Background work
posts completions back via `post_to_main`.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time as _time
from collections import deque
from enum import Enum
from typing import Callable, Optional


class ClockMode(Enum):
    REAL_TIME = 0
    VIRTUAL_TIME = 1


def real_monotonic() -> float:
    """Wall-clock monotonic seconds. The ONE sanctioned escape hatch for
    code that measures real elapsed time with no app clock injected
    (breaker defaults, archive backoff defaults): routing through here
    keeps `time.monotonic` call sites out of subsystem modules, where
    the D1 static rule (stellar_core_tpu/analysis) would flag them."""
    return _time.monotonic()


def real_perf_counter() -> float:
    """Wall-clock perf_counter; same contract as real_monotonic."""
    return _time.perf_counter()


class _Event:
    __slots__ = ("when", "seq", "fn", "cancelled")

    def __init__(self, when: float, seq: int, fn: Callable[[], None]) -> None:
        self.when = when
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def __lt__(self, other: "_Event") -> bool:
        return (self.when, self.seq) < (other.when, other.seq)


class VirtualClock:
    """Deterministic (virtual) or real-time event loop.

    - `post(fn)`: run fn on the next crank (FIFO "action queue").
    - `post_to_main(fn)`: thread-safe variant for worker threads.
    - timers via VirtualTimer.
    - `crank(block)`: run due actions/timers; in VIRTUAL mode, if nothing is
      due and timers exist, time jumps to the next deadline.
    """

    def __init__(self, mode: ClockMode = ClockMode.VIRTUAL_TIME) -> None:
        self.mode = mode
        self._virtual_now = 0.0
        self._seq = itertools.count()
        self._timers: list[_Event] = []
        self._actions: deque[Callable[[], None]] = deque()
        self._xq_lock = threading.Lock()
        self._xq: deque[Callable[[], None]] = deque()
        self._stopped = False

    # -- time ---------------------------------------------------------------
    def now(self) -> float:
        if self.mode == ClockMode.REAL_TIME:
            return _time.monotonic()
        return self._virtual_now

    def system_now(self) -> int:
        """Wall-clock seconds (close times). Virtual mode derives it from
        virtual time so tests are deterministic."""
        if self.mode == ClockMode.REAL_TIME:
            return int(_time.time())
        return int(self._virtual_now)

    def set_virtual_time(self, t: float) -> None:
        assert self.mode == ClockMode.VIRTUAL_TIME
        assert t >= self._virtual_now
        self._virtual_now = t

    # -- scheduling ---------------------------------------------------------
    def post(self, fn: Callable[[], None]) -> None:
        self._actions.append(fn)

    def post_to_main(self, fn: Callable[[], None]) -> None:
        with self._xq_lock:
            self._xq.append(fn)

    def _schedule(self, when: float, fn: Callable[[], None]) -> _Event:
        ev = _Event(when, next(self._seq), fn)
        heapq.heappush(self._timers, ev)
        return ev

    # -- crank --------------------------------------------------------------
    def _drain_cross_thread(self) -> None:
        with self._xq_lock:
            while self._xq:
                self._actions.append(self._xq.popleft())

    def crank(self, block: bool = False) -> int:
        """Run pending work. Returns number of handlers executed."""
        if self._stopped:
            return 0
        n = 0
        self._drain_cross_thread()

        # run all queued actions (they may enqueue more; run snapshot)
        for _ in range(len(self._actions)):
            fn = self._actions.popleft()
            fn()
            n += 1

        # fire due timers
        nowt = self.now()
        while self._timers and self._timers[0].when <= nowt:
            ev = heapq.heappop(self._timers)
            if not ev.cancelled:
                ev.fn()
                n += 1

        if n:
            return n

        # nothing due: advance (virtual) or wait (real) if blocking
        self._prune_cancelled()
        if self._timers:
            nxt = self._timers[0].when
            if self.mode == ClockMode.VIRTUAL_TIME:
                self._virtual_now = max(self._virtual_now, nxt)
                while self._timers and self._timers[0].when <= self._virtual_now:
                    ev = heapq.heappop(self._timers)
                    if not ev.cancelled:
                        ev.fn()
                        n += 1
            elif block:
                _time.sleep(min(max(nxt - nowt, 0.0), 0.050))
        elif block and self.mode == ClockMode.REAL_TIME:
            _time.sleep(0.001)
        return n

    def crank_ready(self) -> int:
        """Run queued actions and already-due timers WITHOUT advancing
        virtual time (used by manual-close style synchronous drains)."""
        if self._stopped:
            return 0
        n = 0
        self._drain_cross_thread()
        for _ in range(len(self._actions)):
            self._actions.popleft()()
            n += 1
        nowt = self.now()
        while self._timers and self._timers[0].when <= nowt:
            ev = heapq.heappop(self._timers)
            if not ev.cancelled:
                ev.fn()
                n += 1
        return n

    def _prune_cancelled(self) -> None:
        if self._timers and all(e.cancelled for e in self._timers):
            self._timers.clear()

    def stop(self) -> None:
        self._stopped = True

    @property
    def stopped(self) -> bool:
        return self._stopped


class VirtualTimer:
    """Cancellable one-shot timer bound to a VirtualClock.

    Role parity: reference VirtualTimer (src/util/Timer.h:244): expires_at /
    expires_from_now + async_wait(on_fire, on_cancel); cancel() invokes the
    error handler (reference passes asio error codes; we pass a flag).
    """

    def __init__(self, clock: VirtualClock) -> None:
        self._clock = clock
        self._ev: Optional[_Event] = None
        self._deadline = 0.0

    @property
    def deadline(self) -> float:
        return self._deadline

    def expires_from_now(self, delay: float) -> None:
        self.cancel()
        self._deadline = self._clock.now() + delay

    def expires_at(self, when: float) -> None:
        self.cancel()
        self._deadline = when

    def async_wait(self, on_fire: Callable[[], None],
                   on_cancel: Optional[Callable[[], None]] = None) -> None:
        self.cancel()
        ev_holder = {}

        def fire() -> None:
            if ev_holder["ev"].cancelled:
                return
            self._ev = None
            on_fire()

        ev = self._clock._schedule(self._deadline, fire)
        ev_holder["ev"] = ev
        self._ev = ev
        self._on_cancel = on_cancel

    def cancel(self) -> None:
        if self._ev is not None:
            self._ev.cancelled = True
            self._ev = None
            cb = getattr(self, "_on_cancel", None)
            self._on_cancel = None
            if cb is not None:
                cb()

    @property
    def seated(self) -> bool:
        return self._ev is not None
