"""Metrics registry: counters, meters, timers, histograms.

Role parity: reference libmedida (`src/main/Application.h:182-194`,
docs/metrics.md) — per-app registry, exported as JSON via the HTTP admin
`/metrics` endpoint. Rates are computed from a sliding window rather than
EWMA; percentiles from a bounded reservoir.
"""

from __future__ import annotations

import bisect
import time
from collections import deque
from typing import Deque, Callable, Dict, List


class Counter:
    def __init__(self) -> None:
        self.count = 0

    def inc(self, n: int = 1) -> None:
        self.count += n

    def dec(self, n: int = 1) -> None:
        self.count -= n

    def set_count(self, n: int) -> None:
        self.count = n

    def to_json(self) -> dict:
        return {"type": "counter", "count": self.count}


class Meter:
    """Event-rate meter. Events aggregate into per-second buckets held in
    a deque, so mark() is O(1) amortized and memory is bounded by the
    15-minute window regardless of event rate — these sit on hot paths
    (tx intake, SCP receive, flood)."""

    def __init__(self, now_fn: Callable[[], float]) -> None:
        self._now = now_fn
        self.count = 0
        self._buckets: Deque[tuple[int, int]] = deque()  # (sec, n)

    def mark(self, n: int = 1) -> None:
        self.count += n
        sec = int(self._now())
        b = self._buckets
        if b and b[-1][0] == sec:
            b[-1] = (sec, b[-1][1] + n)
        else:
            b.append((sec, n))
            self._prune(sec)

    def _prune(self, sec: int) -> None:
        cutoff = sec - 900
        b = self._buckets
        while b and b[0][0] < cutoff:
            b.popleft()

    def rate(self, window: float) -> float:
        t = self._now()
        # prune on reads too: an idle meter must decay to 0 and drop its
        # stale buckets, not report them forever
        self._prune(int(t))
        total = sum(n for (sec, n) in self._buckets if sec >= t - window)
        return total / window if window > 0 else 0.0

    def one_minute_rate(self) -> float:
        return self.rate(60.0)

    def to_json(self) -> dict:
        return {"type": "meter", "count": self.count,
                "1_min_rate": self.one_minute_rate(),
                "5_min_rate": self.rate(300.0),
                "15_min_rate": self.rate(900.0)}


class Histogram:
    MAX_SAMPLES = 1028

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples: List[float] = []
        self._i = 0

    def update(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if len(self._samples) < self.MAX_SAMPLES:
            self._samples.append(v)
        else:
            # deterministic ring replacement keeps a recent-biased reservoir
            self._samples[self._i % self.MAX_SAMPLES] = v
            self._i += 1

    @staticmethod
    def _pick(sorted_samples: List[float], q: float) -> float:
        if not sorted_samples:
            return 0.0
        idx = min(int(q * len(sorted_samples)), len(sorted_samples) - 1)
        return sorted_samples[idx]

    def percentile(self, q: float) -> float:
        return self._pick(sorted(self._samples), q)

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def to_json(self) -> dict:
        # one sort shared by every percentile in the export
        s = sorted(self._samples)
        return {"type": "histogram", "count": self.count, "mean": self.mean(),
                "min": self.min or 0.0, "max": self.max or 0.0,
                "median": self._pick(s, 0.5), "p75": self._pick(s, 0.75),
                "p95": self._pick(s, 0.95), "p99": self._pick(s, 0.99)}


class Timer(Histogram):
    """Histogram of durations (seconds) + a context-manager helper.

    Durations are measured with the registry's injected `now_fn` so
    virtual-clock tests control them; `perf_counter` is only the
    default when no clock was injected."""

    def __init__(self, now_fn: Callable[[], float] | None = None) -> None:
        super().__init__()
        self._now = now_fn or time.perf_counter

    class _Ctx:
        def __init__(self, t: "Timer") -> None:
            self._t = t

        def __enter__(self):
            self._start = self._t._now()
            return self

        def __exit__(self, *exc):
            self._t.update(self._t._now() - self._start)
            return False

    def time(self) -> "Timer._Ctx":
        return Timer._Ctx(self)

    def to_json(self) -> dict:
        d = super().to_json()
        d["type"] = "timer"
        return d


class MetricsRegistry:
    def __init__(self, now_fn: Callable[[], float] | None = None) -> None:
        self._now = now_fn or time.monotonic
        # timers measure with the injected clock (virtual-clock tests
        # control durations); with no injection they keep perf_counter
        self._timer_now = now_fn
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, factory):
        m = self._metrics.get(name)
        if m is None:
            m = factory()
            self._metrics[name] = m
        return m

    def new_counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def new_meter(self, name: str) -> Meter:
        return self._get(name, lambda: Meter(self._now))

    def new_timer(self, name: str) -> Timer:
        return self._get(name, lambda: Timer(self._timer_now))

    def new_histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def to_json(self, prefix: str | None = None) -> dict:
        """Export the registry; with `prefix`, serialize only metrics
        whose name starts with it (the admin `metrics?filter=` path —
        operators fetching `crypto.` must not pay for `ledger.*`)."""
        return {name: m.to_json()
                for name, m in sorted(self._metrics.items())
                if prefix is None or name.startswith(prefix)}
