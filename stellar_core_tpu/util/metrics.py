"""Metrics registry: counters, meters, timers, histograms.

Role parity: reference libmedida (`src/main/Application.h:182-194`,
docs/metrics.md) — per-app registry, exported as JSON via the HTTP admin
`/metrics` endpoint. Rates are computed from a sliding window rather than
EWMA; percentiles from a bounded reservoir.
"""

from __future__ import annotations

import bisect
import os
import re
import threading
import time
from collections import deque
from typing import Deque, Callable, Dict, List, Optional, Tuple


class Counter:
    def __init__(self) -> None:
        self.count = 0

    def inc(self, n: int = 1) -> None:
        self.count += n

    def dec(self, n: int = 1) -> None:
        self.count -= n

    def set_count(self, n: int) -> None:
        self.count = n

    def to_json(self) -> dict:
        return {"type": "counter", "count": self.count}


class Gauge:
    """Point-in-time value (queue depth, warmup state, occupancy): `set`
    overwrites; there is no history. Exported as a Prometheus gauge —
    the natural shape for the verifier-cockpit instants
    (docs/metrics.md#device-cockpit-gauges)."""

    def __init__(self) -> None:
        self.value: float = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n

    def to_json(self) -> dict:
        return {"type": "gauge", "value": self.value}


class Meter:
    """Event-rate meter. Events aggregate into per-second buckets held in
    a deque, so mark() is O(1) amortized and memory is bounded by the
    15-minute window regardless of event rate — these sit on hot paths
    (tx intake, SCP receive, flood)."""

    def __init__(self, now_fn: Callable[[], float]) -> None:
        self._now = now_fn
        self.count = 0
        self._buckets: Deque[tuple[int, int]] = deque()  # (sec, n)

    def mark(self, n: int = 1) -> None:
        self.count += n
        sec = int(self._now())
        b = self._buckets
        if b and b[-1][0] == sec:
            b[-1] = (sec, b[-1][1] + n)
        else:
            b.append((sec, n))
            self._prune(sec)

    def _prune(self, sec: int) -> None:
        cutoff = sec - 900
        b = self._buckets
        while b and b[0][0] < cutoff:
            b.popleft()

    def rate(self, window: float) -> float:
        t = self._now()
        # prune on reads too: an idle meter must decay to 0 and drop its
        # stale buckets, not report them forever
        self._prune(int(t))
        total = sum(n for (sec, n) in self._buckets if sec >= t - window)
        return total / window if window > 0 else 0.0

    def one_minute_rate(self) -> float:
        return self.rate(60.0)

    def to_json(self) -> dict:
        return {"type": "meter", "count": self.count,
                "1_min_rate": self.one_minute_rate(),
                "5_min_rate": self.rate(300.0),
                "15_min_rate": self.rate(900.0)}


class Histogram:
    MAX_SAMPLES = 1028

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples: List[float] = []
        self._i = 0
        # update vs snapshot: worker threads (threaded verify dispatch)
        # update timers while the main loop exports — the lock makes the
        # count/sum/reservoir capture one consistent cut (the sort runs
        # on the copy, outside the lock)
        self._lock = threading.Lock()

    def update(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._samples) < self.MAX_SAMPLES:
                self._samples.append(v)
            else:
                # deterministic ring replacement keeps a recent-biased
                # reservoir
                self._samples[self._i % self.MAX_SAMPLES] = v
                self._i += 1

    @staticmethod
    def _pick(sorted_samples: List[float], q: float) -> float:
        if not sorted_samples:
            return 0.0
        idx = min(int(q * len(sorted_samples)), len(sorted_samples) - 1)
        return sorted_samples[idx]

    def percentile(self, q: float) -> float:
        return self._pick(sorted(self._samples), q)

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def snapshot(self) -> dict:
        """Atomic export: count/sum/min/max and the reservoir are
        captured under the update lock, then the (single) sort runs on
        the captured copy — so the quantiles always describe exactly the
        population `count` reports, even with worker threads updating
        mid-export. Every exporter (JSON, Prometheus) goes through
        here."""
        with self._lock:
            count, total = self.count, self.total
            mn, mx = self.min, self.max
            samples = list(self._samples)
        s = sorted(samples)
        return {"count": count, "sum": total,
                "mean": (total / count) if count else 0.0,
                "min": mn or 0.0, "max": mx or 0.0,
                "median": self._pick(s, 0.5), "p75": self._pick(s, 0.75),
                "p95": self._pick(s, 0.95), "p99": self._pick(s, 0.99)}

    def to_json(self) -> dict:
        snap = self.snapshot()
        del snap["sum"]
        return {"type": "histogram", **snap}


class Timer(Histogram):
    """Histogram of durations (seconds) + a context-manager helper.

    Durations are measured with the registry's injected `now_fn` so
    virtual-clock tests control them; `perf_counter` is only the
    default when no clock was injected."""

    def __init__(self, now_fn: Callable[[], float] | None = None) -> None:
        super().__init__()
        self._now = now_fn or time.perf_counter

    class _Ctx:
        def __init__(self, t: "Timer") -> None:
            self._t = t

        def __enter__(self):
            self._start = self._t._now()
            return self

        def __exit__(self, *exc):
            self._t.update(self._t._now() - self._start)
            return False

    def time(self) -> "Timer._Ctx":
        return Timer._Ctx(self)

    def to_json(self) -> dict:
        d = super().to_json()
        d["type"] = "timer"
        return d


class MetricsRegistry:
    def __init__(self, now_fn: Callable[[], float] | None = None) -> None:
        self._now = now_fn or time.monotonic
        # timers measure with the injected clock (virtual-clock tests
        # control durations); with no injection they keep perf_counter
        self._timer_now = now_fn
        self._metrics: Dict[str, object] = {}
        # first-use registration can happen on worker threads (threaded
        # verify dispatch registering a per-backend/per-bucket cockpit
        # series) while the admin HTTP path iterates the registry for a
        # scrape — inserts and the export snapshot synchronize here; the
        # hot already-registered path stays a lock-free dict get
        self._reg_lock = threading.Lock()

    def _get(self, name: str, factory):
        m = self._metrics.get(name)
        if m is None:
            with self._reg_lock:
                m = self._metrics.get(name)
                if m is None:
                    m = factory()
                    self._metrics[name] = m
        return m

    def new_counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def new_gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def new_meter(self, name: str) -> Meter:
        return self._get(name, lambda: Meter(self._now))

    def new_timer(self, name: str) -> Timer:
        return self._get(name, lambda: Timer(self._timer_now))

    def new_histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def to_json(self, prefix: str | None = None) -> dict:
        """Export the registry; with `prefix`, serialize only metrics
        whose name starts with it (the admin `metrics?filter=` path —
        operators fetching `crypto.` must not pay for `ledger.*`)."""
        with self._reg_lock:
            items = list(self._metrics.items())
        return {name: m.to_json()
                for name, m in sorted(items)
                if prefix is None or name.startswith(prefix)}


# -- Prometheus text exposition (docs/metrics.md#prometheus-exposition) ------
#
# `metrics?format=prometheus` renders the registry (plus the merged
# crypto-boundary extras) in text exposition format 0.0.4 so real
# deployments scrape nodes with stock Prometheus. The renderer consumes
# the *JSON* export, not live metric objects: whatever the JSON endpoint
# says is exactly what Prometheus sees, and the count/quantile pairs
# come from one atomic Histogram.snapshot().

def prometheus_name(name: str, prefix: str = "sct_") -> str:
    """Name mangling: lowercase, every char outside [a-z0-9_] becomes
    `_`, `sct_` namespace prefix, leading digits guarded. Documented in
    docs/metrics.md — the drift-guard test keeps the catalog honest."""
    out = "".join(c if (c.isascii() and (c.isalnum() or c == "_"))
                  else "_" for c in name.lower())
    if out and out[0].isdigit():
        out = "_" + out
    return prefix + out


def _num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


# -- HELP catalog (docs/metrics.md -> `# HELP` lines) -------------------------
#
# Real Prometheus/Grafana setups expect self-describing scrapes. The
# HELP text is sourced from the docs/metrics.md catalog tables (the same
# file the M1 drift guard keeps complete), parsed once per process:
# exact names map directly, dynamic names (`fault.injected.<site>`) map
# by the literal prefix before the first `<...>` placeholder.

class HelpCatalog:
    def __init__(self, exact: Dict[str, str],
                 prefixes: List[Tuple[str, str, str]]) -> None:
        self.exact = exact
        # (prefix, suffix, text), most-specific-first: families that
        # share a placeholder prefix (`overlay.recv.<type>.count` vs
        # `.bytes`) are distinguished by the literal after the
        # placeholder
        self.prefixes = sorted(
            prefixes, key=lambda kv: -(len(kv[0]) + len(kv[1])))

    def lookup(self, name: str) -> Optional[str]:
        t = self.exact.get(name)
        if t is not None:
            return t
        for prefix, suffix, text in self.prefixes:
            if name.startswith(prefix) and name.endswith(suffix) and \
                    len(name) > len(prefix) + len(suffix):
                return text
        return None


_HELP_CATALOG: Optional[HelpCatalog] = None


def _strip_markdown(cell: str) -> str:
    out = cell.replace("\\|", "|").replace("`", "")
    out = re.sub(r"\[([^\]]*)\]\([^)]*\)", r"\1", out)   # [text](link)
    return " ".join(out.split())


def load_help_catalog(path: Optional[str] = None) -> HelpCatalog:
    """Parse docs/metrics.md catalog tables into {metric: help-text}.
    Cached after the first call (the docs ship with the package); a
    missing or unreadable file degrades to an empty catalog — the
    exposition then falls back to the metric name itself."""
    global _HELP_CATALOG
    if _HELP_CATALOG is not None and path is None:
        return _HELP_CATALOG
    cache = path is None
    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "docs", "metrics.md")
    exact: Dict[str, str] = {}
    prefixes: List[Tuple[str, str, str]] = []
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        text = ""
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        cells = re.split(r"(?<!\\)\|", line.strip("|"))
        if len(cells) < 3:
            continue
        names = re.findall(r"`([^`]+)`", cells[0])
        meaning = _strip_markdown(cells[-1])
        if not meaning or meaning.lower() == "meaning":
            continue
        for name in names:
            name = name.strip()
            if not name or name.startswith((".", "-")):
                continue   # shorthand continuation like `-miss`
            if "<" in name:
                prefix = name.split("<", 1)[0]
                suffix = name.rsplit(">", 1)[-1] if ">" in name else ""
                prefixes.append((prefix, suffix, meaning))
            else:
                exact[name] = meaning
    catalog = HelpCatalog(exact, prefixes)
    if cache:
        _HELP_CATALOG = catalog
    return catalog


def _help_text(s: str) -> str:
    # exposition-format escaping for HELP lines
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def render_prometheus(metrics_json: Dict[str, dict],
                      prefix: str = "sct_",
                      help_catalog: Optional[HelpCatalog] = None) -> str:
    """Registry JSON -> exposition text. Mapping:

    - counter / gauge      -> gauge (medida counters can be set/decremented)
    - meter                -> `<n>_total` counter + `<n>_rate{window="1m|5m|15m"}` gauges
    - timer / histogram    -> summary (`quantile` labels, `_sum`, `_count`)
                              + `<n>_min` / `<n>_max` gauges
    - bare `{count: N}`    -> gauge (the merged crypto-boundary extras)

    Two source names that mangle to the same series keep only the
    first (sorted source order); the duplicate is emitted as a comment
    so the collision is visible in the scrape body.

    With `help_catalog` (the admin endpoint passes the docs/metrics.md
    catalog), every `# TYPE` line is preceded by a `# HELP` line whose
    text comes from the catalog where available, falling back to the
    source metric name — real Prometheus/Grafana setups then get
    self-describing scrapes.
    """
    lines: List[str] = []
    emitted: set = set()
    q_map = (("0.5", "median"), ("0.75", "p75"),
             ("0.95", "p95"), ("0.99", "p99"))
    for name in sorted(metrics_json):
        m = metrics_json[name]
        base = prometheus_name(name, prefix)
        t = m.get("type")
        help_text = None
        if help_catalog is not None:
            help_text = _help_text(help_catalog.lookup(name) or name)

        def _help(series: str) -> None:
            if help_text is not None:
                lines.append("# HELP %s %s" % (series, help_text))
        # reserve every series this metric will emit, not just the base:
        # a counter named "foo.total" must not collide with meter "foo"'s
        # generated `foo_total` either
        if t == "meter":
            series = {base + "_total", base + "_rate"}
        elif t in ("timer", "histogram"):
            series = {base, base + "_sum", base + "_count",
                      base + "_min", base + "_max"}
        else:
            series = {base}
        if series & emitted:
            lines.append("# collision: %s maps onto already-emitted "
                         "series %s (skipped)"
                         % (name, sorted(series & emitted)))
            continue
        emitted |= series
        if t == "meter":
            _help(base + "_total")
            lines.append("# TYPE %s_total counter" % base)
            lines.append("%s_total %s" % (base, _num(m["count"])))
            _help(base + "_rate")
            lines.append("# TYPE %s_rate gauge" % base)
            for w, k in (("1m", "1_min_rate"), ("5m", "5_min_rate"),
                         ("15m", "15_min_rate")):
                lines.append('%s_rate{window="%s"} %s'
                             % (base, w, _num(m.get(k, 0.0))))
        elif t in ("timer", "histogram"):
            _help(base)
            lines.append("# TYPE %s summary" % base)
            for q, k in q_map:
                lines.append('%s{quantile="%s"} %s'
                             % (base, q, _num(m.get(k, 0.0))))
            # sum reconstructed from the same snapshot's mean*count —
            # still tear-free because both came from one snapshot()
            lines.append("%s_sum %s" % (
                base, _num(m.get("mean", 0.0) * m.get("count", 0))))
            lines.append("%s_count %s" % (base, _num(m.get("count", 0))))
            for k in ("min", "max"):
                _help("%s_%s" % (base, k))
                lines.append("# TYPE %s_%s gauge" % (base, k))
                lines.append("%s_%s %s" % (base, k, _num(m.get(k, 0.0))))
        elif t == "gauge":
            _help(base)
            lines.append("# TYPE %s gauge" % base)
            lines.append("%s %s" % (base, _num(m.get("value", 0.0))))
        elif "count" in m:   # counter or merged bare-count extra
            _help(base)
            lines.append("# TYPE %s gauge" % base)
            lines.append("%s %s" % (base, _num(m["count"])))
        # anything else (malformed entry) is skipped silently: the JSON
        # endpoint remains the lossless export
    return "\n".join(lines) + "\n"
