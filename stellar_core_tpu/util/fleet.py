"""Fleet aggregation: N nodes' traces + slot timelines on one axis.

Role parity: none in the reference — operators eyeball N dashboards.
The committee-consensus measurement literature (arXiv:2302.00418, DSig
arXiv:2406.07215 in PAPERS.md) attributes commit latency fleet-wide:
propagation and stragglers dominate, per-node compute doesn't. This
module merges every node's span ring (util/tracing.py) and slot
timeline (util/slot_timeline.py) into

- one Chrome-trace JSON with one *process lane per node* (metadata
  `process_name` events), timeline events injected as instants — drop
  the file in chrome://tracing / Perfetto and read a slot across the
  quorum;
- per-slot fleet stats: externalize skew across nodes, flood latency
  from first sender to last receiver, straggler attribution, and
  slot-latency percentiles — what `bench.py --fleet` records as the
  `fleet` block.

Alignment: timeline events carry two stamps (util/slot_timeline.py) —
`t` (per-node app clock) and `pc` (`time.perf_counter()`). In-process
simulations share one perf_counter, so `pc` IS the fleet clock there.
Against live HTTP nodes each host's perf_counter has its own epoch;
the aggregator then rebases each node so its first externalize of the
earliest common slot lands at the same instant (`align="externalize"`),
which preserves intra-node deltas and makes skew read as dispersion
around that anchor — exact cross-host clock sync is explicitly out of
scope (NTP is assumed for wall-clock interpretation).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from .metrics import Histogram

SEEN_SUFFIX = ".seen"


def _percentile(values: List[float], q: float) -> float:
    # one quantile semantics repo-wide: reuse the histogram pick
    return Histogram._pick(sorted(values), q)


class FleetAggregator:
    """Collects per-node observability exports and merges them.

    Nodes are added either in-process (`add_app`, the simulation path)
    or from a live admin API (`add_http`). Every node entry holds the
    same shape: name, node_id (hex), chrome trace dict, timeline JSON,
    optional survey stats — so the merge/stat code has one input form.
    """

    def __init__(self) -> None:
        self.nodes: List[dict] = []

    # -- intake --------------------------------------------------------------
    def add_app(self, name: str, app) -> None:
        survey = None
        om = getattr(app, "overlay_manager", None)
        sm = getattr(om, "survey_manager", None)
        if sm is not None:
            survey = sm.get_stats()
        # wire cockpit (ISSUE 10): per-node overlay bandwidth + tx
        # lifecycle in the same compact shape `overlaystats` serves
        # under its "fleet" field, so add_http stores identical input
        ostats = getattr(om, "stats", None)
        lc = getattr(getattr(app, "herder", None), "tx_lifecycle", None)
        overlay = None
        if ostats is not None or lc is not None:
            overlay = {
                "overlay": ostats.fleet_json()
                if ostats is not None else None,
                "tx": lc.fleet_json() if lc is not None else None,
            }
        # propagation cockpit (ISSUE 17): per-node causal hop records,
        # merged by msg_hash into relay trees below
        prop = getattr(om, "prop_stats", None)
        # consensus cockpit + footprint census (ISSUE 19): per-node
        # envelopes/rounds/phases and the bounded-structure census,
        # merged into the scp_summary / footprint_table blocks below
        ss = getattr(getattr(app, "herder", None), "scp_stats", None)
        fp = getattr(app, "footprint", None)
        self.nodes.append({
            "name": name,
            "node_id": app.config.node_id().key_bytes.hex(),
            "trace": app.tracer.to_chrome_trace(),
            "timeline": app.slot_timeline.to_json(),
            "survey": survey,
            "overlay": overlay,
            "propagation": prop.fleet_json() if prop is not None else None,
            "scp": ss.fleet_json() if ss is not None else None,
            "footprint": fp.fleet_json() if fp is not None else None,
        })

    def add_http(self, base_url: str, name: Optional[str] = None,
                 timeout: float = 5.0) -> None:
        """Aggregate a live node via its admin API: `timeline`,
        `trace?action=dump`, and `getsurveyresult`."""
        from urllib.request import urlopen

        def get(path: str) -> Optional[dict]:
            try:
                with urlopen(base_url.rstrip("/") + path,
                             timeout=timeout) as r:
                    return json.loads(r.read().decode())
            except Exception:
                return None

        tl = get("/timeline")
        if tl is None:
            raise RuntimeError("node %s: timeline endpoint unreachable"
                               % base_url)
        # same compact shape as add_app's get_stats() — the endpoint
        # carries it under "stats" precisely so both intake paths store
        # one input form
        survey = (get("/getsurveyresult") or {}).get("stats")
        self.nodes.append({
            "name": name or tl.get("node") or base_url,
            "node_id": tl.get("node_id"),
            "trace": get("/trace") or {"traceEvents": []},
            "timeline": tl,
            "survey": survey,
            # same compact shape as add_app stores (the endpoint carries
            # it under "fleet" precisely for this intake path)
            "overlay": (get("/overlaystats") or {}).get("fleet"),
            "propagation": (get("/propagation") or {}).get("fleet"),
            "scp": (get("/scpstats") or {}).get("fleet"),
            "footprint": get("/footprint"),
        })

    # -- cross-host alignment ------------------------------------------------
    def rebase_on_externalize(self) -> bool:
        """Live-node alignment: pick the earliest slot every node
        externalized, and shift each node's `pc` stamps (timeline AND
        span ring) so those externalize events coincide. Intra-node
        deltas are preserved; cross-node skew for *other* slots then
        reads as dispersion around the anchor. Returns False (no-op)
        when the nodes share no externalized slot."""
        per_node_ext: List[Dict[int, float]] = []
        for node in self.nodes:
            exts: Dict[int, float] = {}
            tl = node.get("timeline") or {}
            for slot_str, evs in tl.get("slots", {}).items():
                for ev in evs:
                    if ev["event"] == "externalize":
                        exts.setdefault(int(slot_str), ev["pc"])
            per_node_ext.append(exts)
        if not per_node_ext:
            return False
        common = set(per_node_ext[0])
        for exts in per_node_ext[1:]:
            common &= set(exts)
        if not common:
            return False
        anchor = min(common)
        anchors = [exts[anchor] for exts in per_node_ext]
        base = min(anchors)
        for node, at in zip(self.nodes, anchors):
            off = at - base
            if off == 0.0:
                continue
            tl = node.get("timeline") or {}
            for evs in tl.get("slots", {}).values():
                for ev in evs:
                    ev["pc"] -= off
            trace = node.get("trace") or {}
            for ev in trace.get("traceEvents", ()):
                if "ts" in ev:
                    ev["ts"] -= off * 1e6
            # propagation hop stamps ride the same per-node epoch
            prop = node.get("propagation") or {}
            for rec in (prop.get("hashes") or {}).values():
                for hop in rec.get("hops", ()):
                    hop["pc"] -= off
        return True

    # -- name resolution -----------------------------------------------------
    def _id_to_name(self) -> Dict[str, str]:
        return {n["node_id"]: n["name"] for n in self.nodes
                if n.get("node_id")}

    def resolve(self, node_id_hex: Optional[str]) -> str:
        if node_id_hex is None:
            return "?"
        return self._id_to_name().get(node_id_hex, node_id_hex[:8])

    # -- merged Chrome trace -------------------------------------------------
    def merged_chrome_trace(self) -> dict:
        """One process lane per node: every node's span-ring events get
        that node's pid, plus its timeline events injected as instant
        events (`timeline.<event>`, cat `slot`) so the consensus journal
        and the span view line up on one axis."""
        events: List[dict] = []
        dropped = 0
        id2name = self._id_to_name()
        for i, node in enumerate(self.nodes):
            events.append({"name": "process_name", "ph": "M", "pid": i,
                           "tid": 0, "args": {"name": node["name"]}})
            events.append({"name": "process_sort_index", "ph": "M",
                           "pid": i, "tid": 0, "args": {"sort_index": i}})
            trace = node.get("trace") or {}
            dropped += trace.get("dropped_spans", 0)
            for ev in trace.get("traceEvents", ()):
                ev = dict(ev)
                ev["pid"] = i
                events.append(ev)
            tl = node.get("timeline") or {}
            for slot_str, evs in sorted(tl.get("slots", {}).items(),
                                        key=lambda kv: int(kv[0])):
                for ev in evs:
                    args = {k: v for k, v in ev.items()
                            if k not in ("event", "pc")}
                    args["slot"] = int(slot_str)
                    if "node" in args:
                        args["node"] = id2name.get(
                            args["node"], (args["node"] or "?")[:8])
                    events.append({
                        "name": "timeline.%s" % ev["event"],
                        "cat": "slot", "ph": "i", "s": "t",
                        "ts": round(ev["pc"] * 1e6, 1),
                        "pid": i, "tid": 0, "args": args})
        # propagation flow events (ISSUE 17): every reconstructed
        # first-delivery edge becomes a Chrome flow arrow from the
        # sender's lane to the receiver's — one envelope's fan-out reads
        # as connected arrows across node lanes in Perfetto
        pid_of = {node["name"]: i for i, node in enumerate(self.nodes)}
        flow_id = 1
        for hh, tree in sorted(self.propagation_trees().items()):
            for e in tree["first_edges"]:
                if e["latency_s"] is None:
                    continue
                fp, tp = pid_of.get(e["from"]), pid_of.get(e["to"])
                if fp is None or tp is None:
                    continue
                args = {"hash": hh[:16], "slot": tree["ledger_seq"],
                        "from": e["from"], "to": e["to"]}
                name = "prop.%s" % tree["type"]
                events.append({
                    "name": name, "cat": "prop", "ph": "s", "id": flow_id,
                    "pid": fp, "tid": 0, "args": args,
                    "ts": round((e["pc"] - e["latency_s"]) * 1e6, 1)})
                events.append({
                    "name": name, "cat": "prop", "ph": "f", "bp": "e",
                    "id": flow_id, "pid": tp, "tid": 0, "args": args,
                    "ts": round(e["pc"] * 1e6, 1)})
                flow_id += 1
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "dropped_spans": dropped,
                "nodes": [n["name"] for n in self.nodes]}

    # -- per-slot fleet stats ------------------------------------------------
    def _slot_events(self) -> Dict[int, Dict[str, List[dict]]]:
        """slot -> node name -> that node's journal for the slot."""
        out: Dict[int, Dict[str, List[dict]]] = {}
        for node in self.nodes:
            tl = node.get("timeline") or {}
            for slot_str, evs in tl.get("slots", {}).items():
                out.setdefault(int(slot_str), {})[node["name"]] = list(evs)
        return out

    def fleet_stats(self) -> dict:
        """Per-slot cross-node stats + fleet summary percentiles.

        Per slot:
        - `externalize`: skew (max-min externalize `pc` across nodes),
          first/last node, straggler = last node with its lag;
        - `flood`: the earliest envelope-seen event names the first
          *sender* (flood origin); latency runs from that first arrival
          to the last arrival anywhere in the fleet;
        - `slot_latency_s`: first timeline activity anywhere -> last
          externalize anywhere — the whole-quorum slot cost.
        """
        by_slot = self._slot_events()
        id2name = self._id_to_name()
        slots: Dict[str, dict] = {}
        latencies: List[float] = []
        skews: List[float] = []
        stragglers: Dict[str, int] = {}
        for slot in sorted(by_slot):
            per_node = by_slot[slot]
            entry: dict = {}
            ext = {}
            first_pc = None
            for name, evs in per_node.items():
                for ev in evs:
                    pc = ev["pc"]
                    if first_pc is None or pc < first_pc:
                        first_pc = pc
                    if ev["event"] == "externalize" and name not in ext:
                        ext[name] = ev
            if ext:
                ordered = sorted(ext.items(), key=lambda kv: kv[1]["pc"])
                lo, hi = ordered[0], ordered[-1]
                skew = hi[1]["pc"] - lo[1]["pc"]
                entry["externalize"] = {
                    "nodes": len(ext), "skew_s": round(skew, 6),
                    "first": lo[0], "last": hi[0],
                    "straggler": hi[0], "lag_s": round(skew, 6),
                }
                full = len(ext) == len(self.nodes)
                if full and len(self.nodes) > 1:
                    skews.append(skew)
                    stragglers[hi[0]] = stragglers.get(hi[0], 0) + 1
                if first_pc is not None:
                    lat = hi[1]["pc"] - first_pc
                    entry["slot_latency_s"] = round(lat, 6)
                    # summary percentiles only over fully-observed slots:
                    # a slot some node's ring already evicted would feed
                    # a truncated latency and bias p50/p95 downward
                    if full:
                        latencies.append(lat)
            seen = []
            for name, evs in per_node.items():
                for ev in evs:
                    if ev["event"].endswith(SEEN_SUFFIX):
                        seen.append((ev["pc"], name, ev))
            if seen:
                seen.sort(key=lambda t: t[0])
                first = seen[0]
                last = seen[-1]
                entry["flood"] = {
                    "first_sender": id2name.get(
                        first[2].get("node"),
                        (first[2].get("node") or "?")[:8]),
                    "first_seen_by": first[1],
                    "last_seen_by": last[1],
                    "latency_s": round(last[0] - first[0], 6),
                    "arrivals": len(seen),
                }
            if entry:
                slots[str(slot)] = entry
        # per-slot fleet bandwidth: sum each node's per-slot byte deltas
        # (ISSUE 10 — the measurement ROADMAP item 3's 50-100-node
        # envelope-cost study reads per slot)
        for node in self.nodes:
            ov = (node.get("overlay") or {}).get("overlay") or {}
            for slot_str, delta in (ov.get("per_slot") or {}).items():
                entry = slots.get(slot_str)
                if entry is None:
                    continue
                bw = entry.setdefault(
                    "bandwidth", {"recv_bytes": 0, "send_bytes": 0,
                                  "recv_msgs": 0, "send_msgs": 0})
                for k in bw:
                    bw[k] += delta.get(k, 0)
        # per-slot propagation percentiles (ISSUE 17): hop records stamp
        # the LCL at receipt, so messages flooding slot N carry seq N-1
        prop = self.propagation_summary()
        if prop is not None:
            by_ledger: Dict[int, List[dict]] = {}
            for tree in self.propagation_trees().values():
                by_ledger.setdefault(
                    int(tree["ledger_seq"]), []).append(tree)
            for seq, ts_list in by_ledger.items():
                entry = slots.get(str(seq + 1))
                if entry is None:
                    continue
                lat = [e["latency_s"] for t in ts_list
                       for e in t["first_edges"]
                       if e["latency_s"] is not None]
                b = sum(t["bytes"] for t in ts_list)
                w = sum(t["wasted_bytes"] for t in ts_list)
                entry["propagation"] = {
                    "trees": len(ts_list),
                    "hop_latency_p50_ms": round(
                        _percentile(lat, 0.50) * 1e3, 3),
                    "hop_latency_p95_ms": round(
                        _percentile(lat, 0.95) * 1e3, 3),
                    "depth_max": max(t["depth"] for t in ts_list),
                    "redundant_share": round(w / b, 4) if b else 0.0,
                }
        out = {
            "nodes": [n["name"] for n in self.nodes],
            "slots": slots,
            "summary": {
                "slot_count": len(slots),
                "slot_latency_p50_s": round(
                    _percentile(latencies, 0.50), 6),
                "slot_latency_p95_s": round(
                    _percentile(latencies, 0.95), 6),
                "externalize_skew_p50_s": round(
                    _percentile(skews, 0.50), 6),
                "externalize_skew_p95_s": round(
                    _percentile(skews, 0.95), 6),
                "externalize_skew_max_s": round(
                    max(skews), 6) if skews else 0.0,
                "stragglers": stragglers,
            },
        }
        surveys = {n["name"]: n["survey"] for n in self.nodes
                   if n.get("survey")}
        if surveys:
            out["survey"] = surveys
        ob = self.overlay_breakdown()
        if ob is not None:
            out["summary"]["recv_bytes_total"] = ob["recv_bytes"]
            out["summary"]["send_bytes_total"] = ob["send_bytes"]
            out["summary"]["flood_duplication_ratio"] = \
                ob["flood"]["duplication_ratio"]
            out["summary"]["tx_latency_p50_ms"] = ob["tx_latency_ms"]["p50"]
            out["summary"]["tx_latency_p95_ms"] = ob["tx_latency_ms"]["p95"]
        if prop is not None:
            out["propagation"] = prop
            out["summary"]["hop_latency_p95_ms"] = \
                prop["hop_latency_p95_ms"]
            out["summary"]["redundant_bandwidth_share"] = \
                prop["redundant_bandwidth_share"]
        scp = self.scp_summary()
        if scp is not None:
            out["scp"] = scp
            out["summary"]["envelopes_per_slot"] = \
                scp["envelopes_per_slot"]
        fpt = self.footprint_table()
        if fpt is not None:
            out["footprint"] = fpt
            out["summary"]["per_node_rss_mb"] = fpt["per_node_rss_mb"]
        return out

    # -- consensus cockpit merge (ISSUE 19) ----------------------------------
    def scp_summary(self) -> Optional[dict]:
        """Fleet-wide `scp` block for bench/scenario artifacts: the
        committed envelopes-per-slot baseline (fleet-wide receive count
        per externalized slot, averaged over slots every scp-reporting
        node observed — ROADMAP item 1's BLS quorum certificates must
        beat this number), the per-statement-type split, per-slot
        wall/phase latencies (the slowest node's, the fleet's real slot
        cost), and worst round counts. None when no node exported
        consensus-cockpit data."""
        reporting = [n for n in self.nodes if n.get("scp")]
        if not reporting:
            return None
        per_type: Dict[str, int] = {}
        sent_total = recv_total = 0
        slots: Dict[str, dict] = {}
        worst_rounds = {"nomination": 0, "ballot": 0}
        # slot -> [per-node slot records]; only slots EVERY reporting
        # node retains feed the envelopes-per-slot mean (a slot some
        # ring already pruned would undercount the fleet flood)
        by_slot: Dict[int, List[dict]] = {}
        for node in reporting:
            scp = node["scp"]
            t = scp.get("totals") or {}
            sent_total += t.get("sent", 0)
            recv_total += t.get("recv", 0)
            for slot_str, rec in (scp.get("slots") or {}).items():
                by_slot.setdefault(int(slot_str), []).append(rec)
                for k, v in (rec.get("recv") or {}).items():
                    per_type[k] = per_type.get(k, 0) + v
                r = rec.get("rounds") or {}
                for k in worst_rounds:
                    worst_rounds[k] = max(worst_rounds[k], r.get(k, 0))
        env_counts: List[float] = []
        for slot in sorted(by_slot):
            recs = by_slot[slot]
            ext = [r for r in recs if r.get("externalized")]
            if not ext:
                continue
            envelopes = sum(sum((r.get("recv") or {}).values())
                            for r in recs)
            walls = [r["phases"]["wall_s"] for r in recs
                     if r.get("phases") and
                     r["phases"].get("wall_s") is not None]
            entry = {"envelopes": envelopes,
                     "wall_s": round(max(walls), 6) if walls else None,
                     "nodes": len(recs)}
            phases: Dict[str, float] = {}
            for r in recs:
                for p, v in ((r.get("phases") or {}).get("phase_s")
                             or {}).items():
                    if v is not None:
                        phases[p] = max(phases.get(p, 0.0), v)
            if phases:
                entry["phase_s"] = {p: round(v, 6)
                                    for p, v in sorted(phases.items())}
            slots[str(slot)] = entry
            if len(recs) == len(reporting):
                env_counts.append(float(envelopes))
        return {
            "nodes": len(reporting),
            "envelopes_per_slot": round(
                sum(env_counts) / len(env_counts), 3) if env_counts
            else 0.0,
            "per_type": dict(sorted(per_type.items())),
            "sent_total": sent_total,
            "recv_total": recv_total,
            "rounds": worst_rounds,
            "slots": slots,
        }

    # -- footprint census merge (ISSUE 19) -----------------------------------
    def footprint_table(self) -> Optional[dict]:
        """Per-node overhead table + the N-vs-RSS scaling signal for
        `bench.py --fleet-scale`: each node's process stats and every
        registered bounded structure's occupancy/capacity, plus fleet
        totals (`per_node_rss_mb` is the mean — in-process simulations
        share one process, so the sim driver overrides it with the
        measured RSS delta / N; against live HTTP nodes the per-node
        readings are real). None when no node exported a census."""
        reporting = [n for n in self.nodes if n.get("footprint")]
        if not reporting:
            return None
        per_node: Dict[str, dict] = {}
        rss = []
        over: Dict[str, list] = {}
        bytes_total = 0
        for node in reporting:
            fp = node["footprint"]
            proc = fp.get("process") or {}
            rss.append(proc.get("rss_mb", 0.0))
            bytes_total += fp.get("approx_bytes_total", 0)
            if fp.get("over_capacity"):
                over[node["name"]] = list(fp["over_capacity"])
            per_node[node["name"]] = {
                "process": proc,
                "approx_bytes_total": fp.get("approx_bytes_total", 0),
                "structs": {
                    name: {k: v for k, v in entry.items()
                           if k in ("kind", "occupancy", "capacity",
                                    "approx_bytes", "error")}
                    for name, entry in (fp.get("structs") or {}).items()},
            }
        return {
            "nodes": len(reporting),
            "per_node_rss_mb": round(sum(rss) / len(rss), 3),
            "rss_mb_max": round(max(rss), 3) if rss else 0.0,
            "approx_bytes_total": bytes_total,
            "over_capacity": over,
            "per_node": per_node,
        }

    # -- propagation trees (ISSUE 17) ----------------------------------------
    MIN_USEFULNESS_SAMPLES = 4

    def propagation_trees(self) -> Dict[str, dict]:
        """Merge every node's causal hop records by msg_hash into relay
        trees: the origin node (the broadcaster), the first-delivery
        spanning tree (each node's parent = the peer that delivered the
        message first), per-edge hop latency (child first-delivery `pc`
        minus the parent's own first-delivery/origin `pc` — rebase the
        fleet first against live hosts), and the redundant-edge overlay
        (every duplicate receipt, with its wasted bytes). Keys are hash
        hex; `spanning` is True when every receiving node is reachable
        from the origin over first edges."""
        id2name = self._id_to_name()
        merged: Dict[str, dict] = {}
        for node in self.nodes:
            prop = node.get("propagation")
            if not prop:
                continue
            for hh, rec in (prop.get("hashes") or {}).items():
                m = merged.setdefault(hh, {
                    "per_node": {}, "type": rec.get("type"),
                    "ledger_seq": rec.get("ledger_seq", 0)})
                m["per_node"][node["name"]] = rec
        trees: Dict[str, dict] = {}
        for hh, m in merged.items():
            origin = None
            origin_pc = None
            first_pc: Dict[str, float] = {}
            first_parent: Dict[str, str] = {}
            red_edges: List[dict] = []
            firsts = dupes = 0
            bytes_total = wasted = 0
            for name, rec in m["per_node"].items():
                if rec.get("origin"):
                    origin = name
                for hop in rec.get("hops", ()):
                    d = hop.get("dir")
                    if d == "origin":
                        origin_pc = hop["pc"]
                    elif d == "recv":
                        src = id2name.get(hop.get("peer"),
                                          (hop.get("peer") or "?")[:8])
                        bytes_total += hop.get("bytes", 0)
                        if hop.get("first"):
                            firsts += 1
                            if name not in first_pc or \
                                    hop["pc"] < first_pc[name]:
                                first_pc[name] = hop["pc"]
                                first_parent[name] = src
                        else:
                            dupes += 1
                            wasted += hop.get("bytes", 0)
                            red_edges.append({
                                "from": src, "to": name,
                                "bytes": hop.get("bytes", 0)})
            first_edges = []
            for name in sorted(first_pc):
                parent = first_parent[name]
                ppc = origin_pc if parent == origin \
                    else first_pc.get(parent)
                first_edges.append({
                    "from": parent, "to": name,
                    "pc": first_pc[name],
                    "latency_s": (round(first_pc[name] - ppc, 9)
                                  if ppc is not None else None)})
            # BFS from the origin over first edges: per-node depth; the
            # tree depth IS the root's eccentricity
            children: Dict[str, list] = {}
            for e in first_edges:
                children.setdefault(e["from"], []).append(e["to"])
            depths = {origin: 0} if origin is not None else {}
            frontier = [origin] if origin is not None else []
            while frontier:
                nxt = []
                for p in frontier:
                    for c in children.get(p, ()):
                        if c not in depths:
                            depths[c] = depths[p] + 1
                            nxt.append(c)
                frontier = nxt
            depth = max(depths.values()) if depths else 0
            trees[hh] = {
                "type": m["type"], "ledger_seq": m["ledger_seq"],
                "origin": origin,
                "nodes": len(m["per_node"]),
                "firsts": firsts, "duplicates": dupes,
                "bytes": bytes_total, "wasted_bytes": wasted,
                "first_edges": first_edges,
                "redundant_edges": red_edges,
                "depth": depth,
                "spanning": origin is not None and
                len(depths) == len(first_pc) + 1,
            }
        return trees

    def propagation_summary(self) -> Optional[dict]:
        """Fleet-wide `propagation` block for bench/scenario artifacts
        (normalized by tools/bench_compare.py): hop-latency and
        tree-depth percentiles over the reconstructed trees, the
        redundant bandwidth share (wasted bytes / all flooded bytes —
        must reconcile with the flood duplication ratio), and the
        merged per-peer usefulness ranking whose bottom entries are the
        structured relay's first candidates to stop listening to. None
        when no node exported propagation data."""
        trees = self.propagation_trees()
        peers: Dict[str, dict] = {}
        flood_bytes = wasted_total = 0
        firsts_total = dupes_total = 0
        any_data = False
        for node in self.nodes:
            prop = node.get("propagation")
            if not prop:
                continue
            any_data = True
            t = prop.get("totals") or {}
            flood_bytes += t.get("flood_bytes", 0)
            wasted_total += t.get("wasted_bytes", 0)
            firsts_total += t.get("firsts", 0)
            dupes_total += t.get("duplicates", 0)
            for pid, s in (prop.get("peers") or {}).items():
                p = peers.setdefault(pid, {"firsts": 0, "duplicates": 0,
                                           "wasted_bytes": 0})
                for k in p:
                    p[k] += s.get(k, 0)
        if not any_data:
            return None
        id2name = self._id_to_name()
        ranked = []
        for pid, s in peers.items():
            n = s["firsts"] + s["duplicates"]
            ranked.append({
                "peer": id2name.get(pid, pid[:8]),
                "firsts": s["firsts"], "duplicates": s["duplicates"],
                "wasted_bytes": s["wasted_bytes"], "deliveries": n,
                "usefulness": round(s["firsts"] / n, 4) if n else 1.0})
        ranked.sort(key=lambda e: (-e["usefulness"], e["peer"]))
        scored = [e["usefulness"] for e in ranked
                  if e["deliveries"] >= self.MIN_USEFULNESS_SAMPLES]
        lat = [e["latency_s"] for t in trees.values()
               for e in t["first_edges"] if e["latency_s"] is not None]
        depths = [float(t["depth"]) for t in trees.values()
                  if t["origin"] is not None]
        return {
            "trees": len(trees),
            "hop_latency_p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
            "hop_latency_p95_ms": round(_percentile(lat, 0.95) * 1e3, 3),
            "tree_depth_p95": round(_percentile(depths, 0.95), 3),
            "firsts": firsts_total,
            "duplicates": dupes_total,
            "flood_bytes": flood_bytes,
            "wasted_bytes": wasted_total,
            "redundant_bandwidth_share": round(
                wasted_total / flood_bytes, 4) if flood_bytes else 0.0,
            "peers": {
                "worst_usefulness": (round(min(scored), 4)
                                     if scored else None),
                "bottom": ranked[-8:][::-1],
            },
        }

    # -- overlay breakdown (ISSUE 10) ----------------------------------------
    def overlay_breakdown(self) -> Optional[dict]:
        """Fleet-wide `overlay_breakdown` block for bench/scenario
        artifacts (normalized by tools/bench_compare.py): summed
        bandwidth totals, flood dedup (duplication ratio = duplicate
        receipts / unique flooded messages — the O(n²) waste), and the
        tx-lifecycle latency whose stage seconds sum to total_seconds
        by construction. Tx percentiles are computed over the MERGED
        per-node total-latency reservoirs, not merged per-node
        percentiles. None when no node exported overlay data."""
        totals = {"recv_bytes": 0, "send_bytes": 0,
                  "recv_msgs": 0, "send_msgs": 0}
        unique = dupes = 0
        stage: Dict[str, float] = {}
        total_s = 0.0
        count = 0
        samples: List[float] = []
        outcomes: Dict[str, int] = {}
        any_data = False
        for node in self.nodes:
            data = node.get("overlay")
            if not data:
                continue
            ov = data.get("overlay")
            if ov:
                any_data = True
                for k in totals:
                    totals[k] += (ov.get("totals") or {}).get(k, 0)
                fl = ov.get("flood") or {}
                unique += fl.get("unique", 0)
                dupes += fl.get("duplicates", 0)
            tx = data.get("tx")
            if tx:
                any_data = True
                count += tx.get("count", 0)
                total_s += tx.get("total_seconds", 0.0)
                for s, v in (tx.get("stage_seconds") or {}).items():
                    stage[s] = stage.get(s, 0.0) + v
                samples.extend(tx.get("samples_ms") or ())
                for k, v in (tx.get("outcomes") or {}).items():
                    outcomes[k] = outcomes.get(k, 0) + v
        if not any_data:
            return None
        return {
            **totals,
            "flood": {
                "unique": unique, "duplicates": dupes,
                "duplication_ratio": round(
                    dupes / unique if unique else 0.0, 4),
            },
            "tx_latency_ms": {
                "count": count,
                "p50": round(_percentile(samples, 0.50), 3),
                "p95": round(_percentile(samples, 0.95), 3),
            },
            "stage_seconds": {s: round(v, 9)
                              for s, v in sorted(stage.items())},
            "total_seconds": round(total_s, 9),
            "outcomes": dict(sorted(outcomes.items())),
        }
