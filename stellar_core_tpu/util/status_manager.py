"""Rolled-up per-subsystem status lines surfaced in `info`.

Role parity: reference `src/util/StatusManager.{h,cpp}` — a small
category→message map; subsystems keep one human-readable line each
(publish backlog, catchup progress, armed upgrades), and the `info`
endpoint renders them as the "status" array.
"""

from __future__ import annotations

from typing import Dict, Iterator, Tuple

from .log import get_logger

log = get_logger("History")


class StatusCategory:
    HISTORY_CATCHUP = 0
    HISTORY_PUBLISH = 1
    NTP = 2
    REQUIRES_UPGRADES = 3


class StatusManager:
    def __init__(self) -> None:
        self._messages: Dict[int, str] = {}

    def set_status_message(self, category: int, message: str) -> None:
        """Idempotent: a change is logged once, a repeat is silent
        (reference call sites compare before set; centralized here)."""
        if self._messages.get(category) == message:
            return
        self._messages[category] = message
        log.info("%s", message)

    def remove_status_message(self, category: int) -> None:
        self._messages.pop(category, None)

    def get_status_message(self, category: int) -> str:
        return self._messages.get(category, "")

    def __iter__(self) -> Iterator[Tuple[int, str]]:
        return iter(sorted(self._messages.items()))

    def __len__(self) -> int:
        return len(self._messages)

    def to_list(self) -> list:
        """The info endpoint's "status" array (category order, like the
        reference's map iteration)."""
        return [msg for _cat, msg in self]
