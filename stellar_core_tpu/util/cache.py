"""RandomEvictionCache: bounded map with random eviction.

Role parity: reference `src/util/RandomEvictionCache.h` — O(1) insert/lookup,
evicts a uniformly random victim when full (better worst-case than LRU under
adversarial scan patterns, which matters for the signature cache).
"""

from __future__ import annotations

import random
from collections import OrderedDict
from typing import Any, Callable, Dict, Generic, Hashable, List, Optional, \
    TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class RandomEvictionCache(Generic[K, V]):
    def __init__(self, max_size: int, rng: random.Random | None = None) -> None:
        assert max_size > 0
        self._max = max_size
        self._map: Dict[K, int] = {}
        self._keys: List[K] = []
        self._vals: List[V] = []
        self._rng = rng or random.Random(0xC0FFEE)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._keys)

    def __contains__(self, k: K) -> bool:
        return k in self._map

    def exists(self, k: K) -> bool:
        if k in self._map:
            self.hits += 1
            return True
        self.misses += 1
        return False

    def get(self, k: K) -> V:
        i = self._map[k]
        return self._vals[i]

    def maybe_get(self, k: K):
        i = self._map.get(k)
        if i is None:
            self.misses += 1
            return None
        self.hits += 1
        return self._vals[i]

    def put(self, k: K, v: V) -> None:
        i = self._map.get(k)
        if i is not None:
            self._vals[i] = v
            return
        if len(self._keys) >= self._max:
            self._evict_one()
        self._map[k] = len(self._keys)
        self._keys.append(k)
        self._vals.append(v)

    def _evict_one(self) -> None:
        j = self._rng.randrange(len(self._keys))
        self._remove_at(j)
        self.evictions += 1

    def _remove_at(self, j: int) -> None:
        last = len(self._keys) - 1
        victim = self._keys[j]
        if j != last:
            self._keys[j] = self._keys[last]
            self._vals[j] = self._vals[last]
            self._map[self._keys[j]] = j
        self._keys.pop()
        self._vals.pop()
        del self._map[victim]

    def erase(self, k: K) -> bool:
        """Explicit O(1) removal (swap-remove); not counted as an
        eviction. Returns False when the key is absent."""
        j = self._map.get(k)
        if j is None:
            return False
        self._remove_at(j)
        return True

    def keys(self) -> List[K]:
        return list(self._keys)

    def clear(self) -> None:
        self._map.clear()
        self._keys.clear()
        self._vals.clear()


class LRUCache(Generic[K, V]):
    """Bounded map with true least-recently-used eviction (ISSUE 14
    satellite: the root ENTRY cache — unlike the signature cache, its
    access pattern is the txset working set, where LRU beats random
    eviction and, critically, eviction is OBSERVABLE: `on_evict` fires
    per victim so silent coverage loss at 10^6 accounts shows up as
    `ledger.apply.entry-cache.evicted` instead of as a mystery miss
    rate). O(1) get/put via OrderedDict move-to-end."""

    def __init__(self, max_size: int,
                 on_evict: Optional[Callable[[K], None]] = None) -> None:
        assert max_size > 0
        self._max = max_size
        self._od: "OrderedDict[K, V]" = OrderedDict()
        self._on_evict = on_evict
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._od)

    def __contains__(self, k: K) -> bool:
        return k in self._od

    def get(self, k: K) -> V:
        v = self._od[k]
        self._od.move_to_end(k)
        return v

    def maybe_get(self, k: K):
        od = self._od
        if k not in od:
            self.misses += 1
            return None
        self.hits += 1
        od.move_to_end(k)
        return od[k]

    def put(self, k: K, v: V) -> None:
        od = self._od
        if k in od:
            od[k] = v
            od.move_to_end(k)
            return
        while len(od) >= self._max:
            victim, _ = od.popitem(last=False)
            self.evictions += 1
            if self._on_evict is not None:
                self._on_evict(victim)
        od[k] = v

    def clear(self) -> None:
        self._od.clear()
