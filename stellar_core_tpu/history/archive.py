"""HistoryArchive: remote file store reached through operator shell
commands.

Role parity: reference `src/history/HistoryArchive.{h,cpp}` +
`history/readme.md:1-30` — an archive is configured as `get`/`put`/`mkdir`
command templates ({0}=remote path, {1}=local path for get; {0}=local,
{1}=remote for put), so operators plug in curl/aws/cp. Layout
(reference FileTransferInfo.cpp): `<category>/<aa>/<bb>/<cc>/
<category>-<hex8>.xdr.gz` where hex8 is the checkpoint ledger and
aa/bb/cc are its first three hex bytes; HistoryArchiveState JSON at
`.well-known/stellar-history.json` and
`history/<aa>/<bb>/<cc>/history-<hex8>.json`.
"""

from __future__ import annotations

import os
import shlex
import subprocess
from typing import Callable, Dict, List, Optional, Sequence

from ..util.log import get_logger
from ..util.timer import real_monotonic

log = get_logger("History")


def hex8(n: int) -> str:
    return "%08x" % n


def category_path(category: str, checkpoint: int, suffix: str) -> str:
    h = hex8(checkpoint)
    return "%s/%s/%s/%s/%s-%s%s" % (category, h[0:2], h[2:4], h[4:6],
                                    category, h, suffix)


def bucket_path(hash_hex: str) -> str:
    return "bucket/%s/%s/%s/bucket-%s.xdr.gz" % (
        hash_hex[0:2], hash_hex[2:4], hash_hex[4:6], hash_hex)


WELL_KNOWN = ".well-known/stellar-history.json"


class HistoryArchive:
    """One configured archive. Commands run as subprocesses (reference
    runs them through ProcessManager); a plain directory path works too
    (file archive: cp/mkdir fallbacks)."""

    def __init__(self, name: str, get_tmpl: str = "", put_tmpl: str = "",
                 mkdir_tmpl: str = "") -> None:
        self.name = name
        self.get_tmpl = get_tmpl
        self.put_tmpl = put_tmpl
        self.mkdir_tmpl = mkdir_tmpl

    @classmethod
    def from_config(cls, name: str, d: dict) -> "HistoryArchive":
        return cls(name, d.get("get", ""), d.get("put", ""),
                   d.get("mkdir", ""))

    @classmethod
    def local_dir(cls, name: str, root: str) -> "HistoryArchive":
        """file:// archive rooted at a directory (the reference test
        archives use exactly this shape)."""
        root = os.path.abspath(root)
        return cls(name,
                   get_tmpl="cp %s/{0} {1}" % shlex.quote(root),
                   put_tmpl="cp {0} %s/{1}" % shlex.quote(root),
                   mkdir_tmpl="mkdir -p %s/{0}" % shlex.quote(root))

    def has_get(self) -> bool:
        return bool(self.get_tmpl)

    def has_put(self) -> bool:
        return bool(self.put_tmpl)

    # -- command builders (used by history works) ----------------------------
    def get_cmd(self, remote: str, local: str) -> str:
        return self.get_tmpl.replace("{0}", shlex.quote(remote)) \
                            .replace("{1}", shlex.quote(local))

    def put_cmd(self, local: str, remote: str) -> str:
        return self.put_tmpl.replace("{0}", shlex.quote(local)) \
                            .replace("{1}", shlex.quote(remote))

    def mkdir_cmd(self, remote_dir: str) -> str:
        return self.mkdir_tmpl.replace("{0}", shlex.quote(remote_dir))

    # -- synchronous conveniences (CLI paths, tests) -------------------------
    def get_file_sync(self, remote: str, local: str) -> bool:
        cmd = self.get_cmd(remote, local)
        r = subprocess.run(cmd, shell=True, capture_output=True)
        return r.returncode == 0

    def put_file_sync(self, local: str, remote: str) -> bool:
        if self.mkdir_tmpl:
            d = os.path.dirname(remote)
            if d:
                subprocess.run(self.mkdir_cmd(d), shell=True,
                               capture_output=True)
        r = subprocess.run(self.put_cmd(local, remote), shell=True,
                           capture_output=True)
        return r.returncode == 0


class _ArchiveHealth:
    """Per-archive failure bookkeeping inside an ArchivePool."""

    __slots__ = ("successes", "failures", "consecutive_failures",
                 "next_attempt", "last_error_at")

    def __init__(self) -> None:
        self.successes = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.next_attempt = 0.0
        self.last_error_at = 0.0

    def score(self) -> float:
        """Success fraction, optimistic for the untried (a fresh archive
        should be probed before a known-flaky one is retried)."""
        total = self.successes + self.failures
        return (self.successes + 1.0) / (total + 1.0)

    def to_json(self) -> dict:
        return {"successes": self.successes, "failures": self.failures,
                "consecutive_failures": self.consecutive_failures,
                "score": round(self.score(), 3),
                "next_attempt": self.next_attempt}


class ArchivePool:
    """Multi-archive failover for history downloads (docs/robustness.md).

    Tracks a health score per archive and an exponential backoff on
    consecutive failures; `pick()` returns the healthiest archive that
    is not backing off, excluding names the caller already tried for the
    current file. When every archive is excluded or backing off it
    returns the least-bad one anyway — liveness beats politeness when
    the whole archive set is flaky. Works that hold a pool re-pick on
    every retry, so a corrupt or short download from archive A is
    re-fetched from archive B."""

    BACKOFF_BASE = 2.0
    BACKOFF_CAP = 300.0

    def __init__(self, archives: Sequence[HistoryArchive],
                 now_fn: Optional[Callable[[], float]] = None,
                 metrics=None) -> None:
        self.archives: List[HistoryArchive] = list(archives)
        self._by_name: Dict[str, HistoryArchive] = {
            a.name: a for a in self.archives}
        self._health: Dict[str, _ArchiveHealth] = {
            a.name: _ArchiveHealth() for a in self.archives}
        self._now = now_fn or real_monotonic
        self.metrics = metrics
        self.failovers = 0

    # a pool quacks enough like an archive for works that only read gets
    def has_get(self) -> bool:
        return any(a.has_get() for a in self.archives)

    def health(self, name: str) -> _ArchiveHealth:
        return self._health[name]

    def pick(self, exclude: Sequence[str] = ()) -> Optional[HistoryArchive]:
        if not self.archives:
            return None
        now = self._now()
        ex = set(exclude)
        ready = [a for a in self.archives
                 if a.name not in ex
                 and self._health[a.name].next_attempt <= now]
        if ready:
            best = max(ready, key=lambda a: (self._health[a.name].score(),
                                             a.name))
            return best
        # everyone tried or backing off: least consecutive failures wins
        # (ignore both the exclusion and the backoff rather than stall)
        return min(self.archives,
                   key=lambda a: (self._health[a.name].consecutive_failures,
                                  a.name))

    def report_success(self, archive: HistoryArchive) -> None:
        h = self._health.get(archive.name)
        if h is None:
            return
        h.successes += 1
        h.consecutive_failures = 0
        h.next_attempt = 0.0

    def report_failure(self, archive: HistoryArchive) -> None:
        h = self._health.get(archive.name)
        if h is None:
            return
        h.failures += 1
        h.consecutive_failures += 1
        h.last_error_at = self._now()
        h.next_attempt = self._now() + min(
            self.BACKOFF_CAP,
            self.BACKOFF_BASE * (2.0 ** (h.consecutive_failures - 1)))
        if len(self.archives) > 1:
            self.failovers += 1
        if self.metrics is not None:
            self.metrics.new_meter(
                "history.archive.failure.%s" % archive.name).mark()
        log.warning("archive %s failed (%d consecutive); next attempt "
                    "in %.0fs", archive.name, h.consecutive_failures,
                    h.next_attempt - self._now())

    def to_json(self) -> dict:
        return {"archives": {n: h.to_json()
                             for n, h in sorted(self._health.items())},
                "failovers": self.failovers}


