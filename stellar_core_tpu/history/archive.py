"""HistoryArchive: remote file store reached through operator shell
commands.

Role parity: reference `src/history/HistoryArchive.{h,cpp}` +
`history/readme.md:1-30` — an archive is configured as `get`/`put`/`mkdir`
command templates ({0}=remote path, {1}=local path for get; {0}=local,
{1}=remote for put), so operators plug in curl/aws/cp. Layout
(reference FileTransferInfo.cpp): `<category>/<aa>/<bb>/<cc>/
<category>-<hex8>.xdr.gz` where hex8 is the checkpoint ledger and
aa/bb/cc are its first three hex bytes; HistoryArchiveState JSON at
`.well-known/stellar-history.json` and
`history/<aa>/<bb>/<cc>/history-<hex8>.json`.
"""

from __future__ import annotations

import os
import shlex
import subprocess
from typing import Callable, Optional

from ..util.log import get_logger

log = get_logger("History")


def hex8(n: int) -> str:
    return "%08x" % n


def category_path(category: str, checkpoint: int, suffix: str) -> str:
    h = hex8(checkpoint)
    return "%s/%s/%s/%s/%s-%s%s" % (category, h[0:2], h[2:4], h[4:6],
                                    category, h, suffix)


def bucket_path(hash_hex: str) -> str:
    return "bucket/%s/%s/%s/bucket-%s.xdr.gz" % (
        hash_hex[0:2], hash_hex[2:4], hash_hex[4:6], hash_hex)


WELL_KNOWN = ".well-known/stellar-history.json"


class HistoryArchive:
    """One configured archive. Commands run as subprocesses (reference
    runs them through ProcessManager); a plain directory path works too
    (file archive: cp/mkdir fallbacks)."""

    def __init__(self, name: str, get_tmpl: str = "", put_tmpl: str = "",
                 mkdir_tmpl: str = "") -> None:
        self.name = name
        self.get_tmpl = get_tmpl
        self.put_tmpl = put_tmpl
        self.mkdir_tmpl = mkdir_tmpl

    @classmethod
    def from_config(cls, name: str, d: dict) -> "HistoryArchive":
        return cls(name, d.get("get", ""), d.get("put", ""),
                   d.get("mkdir", ""))

    @classmethod
    def local_dir(cls, name: str, root: str) -> "HistoryArchive":
        """file:// archive rooted at a directory (the reference test
        archives use exactly this shape)."""
        root = os.path.abspath(root)
        return cls(name,
                   get_tmpl="cp %s/{0} {1}" % shlex.quote(root),
                   put_tmpl="cp {0} %s/{1}" % shlex.quote(root),
                   mkdir_tmpl="mkdir -p %s/{0}" % shlex.quote(root))

    def has_get(self) -> bool:
        return bool(self.get_tmpl)

    def has_put(self) -> bool:
        return bool(self.put_tmpl)

    # -- command builders (used by history works) ----------------------------
    def get_cmd(self, remote: str, local: str) -> str:
        return self.get_tmpl.replace("{0}", shlex.quote(remote)) \
                            .replace("{1}", shlex.quote(local))

    def put_cmd(self, local: str, remote: str) -> str:
        return self.put_tmpl.replace("{0}", shlex.quote(local)) \
                            .replace("{1}", shlex.quote(remote))

    def mkdir_cmd(self, remote_dir: str) -> str:
        return self.mkdir_tmpl.replace("{0}", shlex.quote(remote_dir))

    # -- synchronous conveniences (CLI paths, tests) -------------------------
    def get_file_sync(self, remote: str, local: str) -> bool:
        cmd = self.get_cmd(remote, local)
        r = subprocess.run(cmd, shell=True, capture_output=True)
        return r.returncode == 0

    def put_file_sync(self, local: str, remote: str) -> bool:
        if self.mkdir_tmpl:
            d = os.path.dirname(remote)
            if d:
                subprocess.run(self.mkdir_cmd(d), shell=True,
                               capture_output=True)
        r = subprocess.run(self.put_cmd(local, remote), shell=True,
                           capture_output=True)
        return r.returncode == 0
