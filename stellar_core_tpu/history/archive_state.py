"""HistoryArchiveState: the JSON manifest naming a checkpoint's buckets.

Role parity: reference `src/history/HistoryArchive.{h,cpp}` (HAS struct,
cereal-serialized) — version, server string, currentLedger, and one
{curr, snap, next} hash triple per bucket level. `next` captures an
in-flight merge so restarts can resume it (reference FutureBucket
serialization states: clear / hashes / live-output).
"""

from __future__ import annotations

import json
from typing import List, Optional

HAS_VERSION = 1
ZERO = "0" * 64


class HASLevel:
    def __init__(self, curr: str = ZERO, snap: str = ZERO,
                 next_state: int = 0,
                 next_output: Optional[str] = None) -> None:
        self.curr = curr
        self.snap = snap
        self.next_state = next_state
        self.next_output = next_output

    def to_dict(self) -> dict:
        nxt: dict = {"state": self.next_state}
        if self.next_output is not None:
            nxt["output"] = self.next_output
        return {"curr": self.curr, "next": nxt, "snap": self.snap}

    @classmethod
    def from_dict(cls, d: dict) -> "HASLevel":
        nxt = d.get("next", {}) or {}
        return cls(d.get("curr", ZERO), d.get("snap", ZERO),
                   nxt.get("state", 0), nxt.get("output"))


class HistoryArchiveState:
    def __init__(self, current_ledger: int = 0,
                 levels: Optional[List[HASLevel]] = None,
                 server: str = "stellar-core-tpu") -> None:
        from ..bucket import K_NUM_LEVELS
        self.version = HAS_VERSION
        self.server = server
        self.current_ledger = current_ledger
        self.levels = levels or [HASLevel() for _ in range(K_NUM_LEVELS)]

    @classmethod
    def from_bucket_list(cls, current_ledger: int, bucket_list,
                         server: str = "stellar-core-tpu"
                         ) -> "HistoryArchiveState":
        levels = []
        for lev in bucket_list.levels:
            nxt_state, nxt_out = 0, None
            if lev.next.is_live() and lev.next.merge_complete():
                nxt_state, nxt_out = 1, lev.next.resolve().get_hash().hex()
            levels.append(HASLevel(lev.curr.get_hash().hex(),
                                   lev.snap.get_hash().hex(),
                                   nxt_state, nxt_out))
        return cls(current_ledger, levels, server)

    def bucket_hashes(self) -> List[str]:
        """Every non-zero hash referenced (reference
        HistoryArchiveState::allBuckets)."""
        out = []
        for lv in self.levels:
            for h in (lv.curr, lv.snap, lv.next_output):
                if h and h != ZERO:
                    out.append(h)
        return out

    def to_json(self) -> str:
        return json.dumps({
            "version": self.version,
            "server": self.server,
            "currentLedger": self.current_ledger,
            "currentBuckets": [lv.to_dict() for lv in self.levels],
        }, indent=2)

    @classmethod
    def from_json(cls, s: str) -> "HistoryArchiveState":
        d = json.loads(s)
        has = cls(d["currentLedger"],
                  [HASLevel.from_dict(x) for x in d["currentBuckets"]],
                  d.get("server", ""))
        has.version = d.get("version", HAS_VERSION)
        return has
