"""HistoryArchiveState: the JSON manifest naming a checkpoint's buckets.

Role parity: reference `src/history/HistoryArchive.{h,cpp}` (HAS struct,
cereal-serialized) — version, server string, currentLedger, and one
{curr, snap, next} hash triple per bucket level. `next` captures an
in-flight merge so restarts can resume it (reference FutureBucket
serialization states: clear / hashes / live-output).
"""

from __future__ import annotations

import json
from typing import List, Optional

HAS_VERSION = 1
ZERO = "0" * 64


class HASLevel:
    """next states mirror the reference FutureBucket serialization:
    0 = clear, 1 = output hash (merge resolved), 2 = input hashes
    (merge in flight: curr/snap/shadows — the only way a pre-12 shadowed
    merge can be resumed after restart/catchup)."""

    def __init__(self, curr: str = ZERO, snap: str = ZERO,
                 next_state: int = 0,
                 next_output: Optional[str] = None,
                 next_curr: Optional[str] = None,
                 next_snap: Optional[str] = None,
                 next_shadows: Optional[List[str]] = None) -> None:
        self.curr = curr
        self.snap = snap
        self.next_state = next_state
        self.next_output = next_output
        self.next_curr = next_curr
        self.next_snap = next_snap
        self.next_shadows = next_shadows or []

    def to_dict(self) -> dict:
        nxt: dict = {"state": self.next_state}
        if self.next_output is not None:
            nxt["output"] = self.next_output
        if self.next_state == 2:
            nxt["curr"] = self.next_curr
            nxt["snap"] = self.next_snap
            nxt["shadow"] = list(self.next_shadows)
        return {"curr": self.curr, "next": nxt, "snap": self.snap}

    @classmethod
    def from_dict(cls, d: dict) -> "HASLevel":
        nxt = d.get("next", {}) or {}
        return cls(d.get("curr", ZERO), d.get("snap", ZERO),
                   nxt.get("state", 0), nxt.get("output"),
                   nxt.get("curr"), nxt.get("snap"),
                   nxt.get("shadow"))


class HistoryArchiveState:
    def __init__(self, current_ledger: int = 0,
                 levels: Optional[List[HASLevel]] = None,
                 server: str = "stellar-core-tpu") -> None:
        from ..bucket import K_NUM_LEVELS
        self.version = HAS_VERSION
        self.server = server
        self.current_ledger = current_ledger
        self.levels = levels or [HASLevel() for _ in range(K_NUM_LEVELS)]

    @classmethod
    def from_bucket_list(cls, current_ledger: int, bucket_list,
                         server: str = "stellar-core-tpu"
                         ) -> "HistoryArchiveState":
        levels = []
        for lev in bucket_list.levels:
            nxt_state, nxt_out = 0, None
            nxt_curr = nxt_snap = None
            nxt_shadows: Optional[List[str]] = None
            if lev.next.is_live() and lev.next.merge_complete():
                nxt_state, nxt_out = 1, lev.next.resolve().get_hash().hex()
            elif lev.next.is_merging() and lev.next.has_hashes():
                # in-flight: record the merge INPUTS so a restart (or a
                # catchup assuming this state) resumes the exact merge —
                # shadowed pre-12 merges are not reconstructible any
                # other way
                nxt_state = 2
                nxt_curr = lev.next.input_curr_hash.hex()
                nxt_snap = lev.next.input_snap_hash.hex()
                nxt_shadows = [h.hex() for h in lev.next.input_shadow_hashes]
            levels.append(HASLevel(lev.curr.get_hash().hex(),
                                   lev.snap.get_hash().hex(),
                                   nxt_state, nxt_out,
                                   nxt_curr, nxt_snap, nxt_shadows))
        return cls(current_ledger, levels, server)

    def bucket_hashes(self) -> List[str]:
        """Every non-zero hash referenced (reference
        HistoryArchiveState::allBuckets) — including in-flight merge
        inputs and shadows, so archives carry what a resume needs."""
        out = []
        for lv in self.levels:
            for h in ((lv.curr, lv.snap, lv.next_output,
                       lv.next_curr, lv.next_snap) +
                      tuple(lv.next_shadows)):
                if h and h != ZERO:
                    out.append(h)
        return out

    def to_json(self) -> str:
        return json.dumps({
            "version": self.version,
            "server": self.server,
            "currentLedger": self.current_ledger,
            "currentBuckets": [lv.to_dict() for lv in self.levels],
        }, indent=2)

    @classmethod
    def from_json(cls, s: str) -> "HistoryArchiveState":
        d = json.loads(s)
        has = cls(d["currentLedger"],
                  [HASLevel.from_dict(x) for x in d["currentBuckets"]],
                  d.get("server", ""))
        has.version = d.get("version", HAS_VERSION)
        return has

def has_level_dicts(has: "HistoryArchiveState") -> List[dict]:
    """HAS levels → the bytes-keyed dicts BucketManager.assume_state
    takes (curr/snap always; next merge as output or inputs+shadows)."""
    out = []
    for lv in has.levels:
        d: dict = {"curr": bytes.fromhex(lv.curr),
                   "snap": bytes.fromhex(lv.snap)}
        if lv.next_state == 1 and lv.next_output:
            d["next_output"] = bytes.fromhex(lv.next_output)
        elif lv.next_state == 2 and lv.next_curr:
            d["next_curr"] = bytes.fromhex(lv.next_curr)
            d["next_snap"] = bytes.fromhex(lv.next_snap)
            d["next_shadows"] = [bytes.fromhex(h)
                                 for h in lv.next_shadows]
        out.append(d)
    return out
