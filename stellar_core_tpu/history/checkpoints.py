"""Checkpoint arithmetic.

Role parity: reference `src/history/HistoryManagerImpl.cpp:85-133` —
history is published in checkpoints of CHECKPOINT_FREQUENCY (64) ledgers;
a checkpoint is named by its last ledger (63, 127, 191, ...; the first
spans genesis..63).
"""

from __future__ import annotations

DEFAULT_FREQUENCY = 64


def checkpoint_containing(ledger: int, freq: int = DEFAULT_FREQUENCY) -> int:
    """Last ledger of the checkpoint that contains `ledger`."""
    return (ledger // freq) * freq + freq - 1


def is_last_in_checkpoint(ledger: int, freq: int = DEFAULT_FREQUENCY) -> bool:
    return (ledger + 1) % freq == 0


def first_in_checkpoint(checkpoint: int,
                        freq: int = DEFAULT_FREQUENCY) -> int:
    """First ledger included in the checkpoint named `checkpoint`
    (genesis checkpoint starts at ledger 1)."""
    assert is_last_in_checkpoint(checkpoint, freq)
    return max(1, checkpoint + 1 - freq)


def prev_checkpoint(checkpoint: int, freq: int = DEFAULT_FREQUENCY) -> int:
    return checkpoint - freq


def checkpoints_in_range(first_ledger: int, last_ledger: int,
                         freq: int = DEFAULT_FREQUENCY):
    """Checkpoint ledgers covering [first_ledger, last_ledger]."""
    c = checkpoint_containing(first_ledger, freq)
    while c - freq + 1 <= last_ledger:
        yield c
        c += freq
