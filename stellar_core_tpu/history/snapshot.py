"""StateSnapshot: materialize one checkpoint's files from the database.

Role parity: reference `src/history/StateSnapshot.{h,cpp}` — per
checkpoint writes four XDR streams (ledger headers, transactions,
results, SCP messages) plus the HistoryArchiveState JSON and the bucket
files it names; reference WriteSnapshotWork runs this on a worker thread.
"""

from __future__ import annotations

import gzip
import os
from typing import List, Optional

from ..crypto.hashing import sha256
from ..util.log import get_logger
from ..util.xdrstream import XDROutputFileStream
from ..xdr import (
    LedgerHeader, LedgerHeaderHistoryEntry, LedgerSCPMessages, SCPEnvelope,
    SCPHistoryEntry, SCPHistoryEntryV0, SCPQuorumSet, TransactionEnvelope,
    TransactionHistoryEntry, TransactionHistoryResultEntry, TransactionSet,
    TransactionResultPair, TransactionResultSet, _Ext,
)
from .archive_state import HistoryArchiveState
from .checkpoints import first_in_checkpoint

log = get_logger("History")


def gzip_file(path: str) -> str:
    out = path + ".gz"
    with open(path, "rb") as f, gzip.open(out, "wb", compresslevel=6) as g:
        g.write(f.read())
    return out


def gunzip_file(path: str) -> str:
    assert path.endswith(".gz")
    out = path[:-3]
    with gzip.open(path, "rb") as g, open(out, "wb") as f:
        f.write(g.read())
    return out


class StateSnapshot:
    """Writes checkpoint files into a staging dir; the publish work then
    gzips and uploads them."""

    def __init__(self, app, checkpoint: int, has: HistoryArchiveState,
                 staging_dir: str) -> None:
        self.app = app
        self.checkpoint = checkpoint
        self.has = has
        self.dir = staging_dir
        os.makedirs(staging_dir, exist_ok=True)

    def _path(self, category: str, suffix: str = ".xdr") -> str:
        return os.path.join(self.dir, "%s-%08x%s"
                            % (category, self.checkpoint, suffix))

    # -- writers -------------------------------------------------------------
    def write_ledger_headers(self) -> str:
        db = self.app.database
        lo = first_in_checkpoint(self.checkpoint,
                                 self.app.config.CHECKPOINT_FREQUENCY)
        path = self._path("ledger")
        with XDROutputFileStream(path) as out:
            for (h, data) in db.execute(
                    "SELECT ledgerhash, data FROM ledgerheaders WHERE "
                    "ledgerseq BETWEEN ? AND ? ORDER BY ledgerseq",
                    (lo, self.checkpoint)).fetchall():
                out.write_one(LedgerHeaderHistoryEntry,
                              LedgerHeaderHistoryEntry(
                                  hash=bytes.fromhex(h),
                                  header=LedgerHeader.from_xdr(data),
                                  ext=_Ext.v0()))
        return path

    def write_transactions(self) -> str:
        db = self.app.database
        lo = first_in_checkpoint(self.checkpoint,
                                 self.app.config.CHECKPOINT_FREQUENCY)
        path = self._path("transactions")
        with XDROutputFileStream(path) as out:
            for seq in range(lo, self.checkpoint + 1):
                rows = db.execute(
                    "SELECT txbody FROM txhistory WHERE ledgerseq = ? "
                    "ORDER BY txindex", (seq,)).fetchall()
                if not rows:
                    continue
                prev = db.execute(
                    "SELECT prevhash FROM ledgerheaders WHERE ledgerseq = ?",
                    (seq,)).fetchone()
                prev_hash = bytes.fromhex(prev[0]) if prev else b"\x00" * 32
                txs = [TransactionEnvelope.from_xdr(r[0]) for r in rows]
                out.write_one(TransactionHistoryEntry, TransactionHistoryEntry(
                    ledgerSeq=seq,
                    txSet=TransactionSet(previousLedgerHash=prev_hash,
                                         txs=txs),
                    ext=_Ext.v0()))
        return path

    def write_results(self) -> str:
        db = self.app.database
        lo = first_in_checkpoint(self.checkpoint,
                                 self.app.config.CHECKPOINT_FREQUENCY)
        path = self._path("results")
        with XDROutputFileStream(path) as out:
            for seq in range(lo, self.checkpoint + 1):
                rows = db.execute(
                    "SELECT txresult FROM txhistory WHERE ledgerseq = ? "
                    "ORDER BY txindex", (seq,)).fetchall()
                if not rows:
                    continue
                results = [TransactionResultPair.from_xdr(r[0])
                           for r in rows]
                out.write_one(
                    TransactionHistoryResultEntry,
                    TransactionHistoryResultEntry(
                        ledgerSeq=seq,
                        txResultSet=TransactionResultSet(results=results),
                        ext=_Ext.v0()))
        return path

    def write_scp_messages(self) -> str:
        db = self.app.database
        lo = first_in_checkpoint(self.checkpoint,
                                 self.app.config.CHECKPOINT_FREQUENCY)
        path = self._path("scp")
        with XDROutputFileStream(path) as out:
            for seq in range(lo, self.checkpoint + 1):
                rows = db.execute(
                    "SELECT envelope FROM scphistory WHERE ledgerseq = ?",
                    (seq,)).fetchall()
                if not rows:
                    continue
                msgs = [SCPEnvelope.from_xdr(r[0]) for r in rows]
                qhashes = set()
                qsets: List[SCPQuorumSet] = []
                for env in msgs:
                    from ..herder.pending_envelopes import statement_qset_hash
                    qh = statement_qset_hash(env.statement)
                    if qh in qhashes:
                        continue
                    qrow = db.execute(
                        "SELECT qset FROM scpquorums WHERE qsethash = ?",
                        (qh.hex(),)).fetchone()
                    if qrow:
                        qhashes.add(qh)
                        qsets.append(SCPQuorumSet.from_xdr(qrow[0]))
                out.write_one(SCPHistoryEntry, SCPHistoryEntry(
                    0, SCPHistoryEntryV0(
                        quorumSets=qsets,
                        ledgerMessages=LedgerSCPMessages(
                            ledgerSeq=seq, messages=msgs))))
        return path

    def write_has(self) -> str:
        path = self._path("history", ".json")
        with open(path, "w") as f:
            f.write(self.has.to_json())
        return path

    def bucket_files(self) -> List[str]:
        """Paths of the bucket files the HAS references (from the bucket
        manager's content-addressed store)."""
        bm = self.app.bucket_manager
        out = []
        if bm is None:
            return out
        for hh in self.has.bucket_hashes():
            b = bm.get_bucket_by_hash(bytes.fromhex(hh))
            if b is None:
                log.warning("snapshot missing bucket %s", hh[:8])
                continue
            if not b.path:
                # in-memory-only store: stage the bucket beside the streams
                p = os.path.join(self.dir, "bucket-%s.xdr" % hh)
                b.write_to(p)
            out.append(b.path)
        return out

    def write_all(self) -> dict:
        return {
            "ledger": self.write_ledger_headers(),
            "transactions": self.write_transactions(),
            "results": self.write_results(),
            "scp": self.write_scp_messages(),
            "has": self.write_has(),
            "buckets": self.bucket_files(),
        }
