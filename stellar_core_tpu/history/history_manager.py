"""HistoryManager: queue and publish checkpoints.

Role parity: reference `src/history/HistoryManagerImpl.{h,cpp}` — every
CHECKPOINT_FREQUENCY ledgers the close path queues a checkpoint inside the
ledger-close DB transaction (crash-safe: LedgerManagerImpl.cpp:681-710),
then publishes after commit via a Work DAG (ResolveSnapshot → Write →
Gzip → Put). Archives with `put` commands receive the files; multiple
archives each get a copy.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..util.log import get_logger
from ..util.tmpdir import TmpDir
from .archive import (ArchivePool, HistoryArchive, WELL_KNOWN, bucket_path,
                      category_path)
from .archive_state import HistoryArchiveState
from .checkpoints import is_last_in_checkpoint
from .snapshot import StateSnapshot, gzip_file

log = get_logger("History")


class HistoryManager:
    def __init__(self, app) -> None:
        self.app = app
        self.archives: Dict[str, HistoryArchive] = {}
        for name, d in app.config.HISTORY.items():
            self.archives[name] = HistoryArchive.from_config(name, d)
        self.publish_queue_dir = TmpDir("history-publish")
        self.published_checkpoints = 0
        self.failed_publishes = 0
        self._readable_pool: Optional[ArchivePool] = None

    # -- archive selection ---------------------------------------------------
    def add_archive(self, archive: HistoryArchive) -> None:
        self.archives[archive.name] = archive
        self._readable_pool = None   # rebuilt on next readable_pool()

    def writable_archives(self) -> List[HistoryArchive]:
        return [a for a in self.archives.values() if a.has_put()]

    def readable_archive(self) -> Optional[HistoryArchive]:
        for a in self.archives.values():
            if a.has_get():
                return a
        return None

    def readable_pool(self) -> Optional[ArchivePool]:
        """All readable archives behind one health-scored failover pool
        (docs/robustness.md). One pool instance per manager, so health
        accumulated by one catchup informs the next."""
        pool = getattr(self, "_readable_pool", None)
        if pool is None:
            readable = [a for a in self.archives.values() if a.has_get()]
            if not readable:
                return None
            pool = ArchivePool(readable, now_fn=self.app.clock.now,
                               metrics=getattr(self.app, "metrics", None))
            self._readable_pool = pool
        return pool

    def has_any_writable_history_archive(self) -> bool:
        return bool(self.writable_archives())

    # -- queueing (called inside ledger close) ------------------------------
    def maybe_queue_checkpoint(self, ledger_manager) -> None:
        seq = ledger_manager.last_closed_ledger_num()
        freq = self.app.config.CHECKPOINT_FREQUENCY
        if not is_last_in_checkpoint(seq, freq):
            return
        if not self.has_any_writable_history_archive():
            return
        db = getattr(self.app, "database", None)
        bm = getattr(self.app, "bucket_manager", None)
        has = HistoryArchiveState.from_bucket_list(
            seq, bm.bucket_list) if bm is not None else \
            HistoryArchiveState(seq)
        if db is not None:
            db.execute(
                "INSERT OR REPLACE INTO publishqueue (ledgerseq, state) "
                "VALUES (?,?)", (seq, has.to_json()))
            db.commit()
        else:
            self._mem_queue = getattr(self, "_mem_queue", {})
            self._mem_queue[seq] = has
        log.info("queued checkpoint %d for publication", seq)
        # publish outside the close path
        self.app.clock.post(self.publish_queued_history)

    def publish_queue(self) -> List[int]:
        db = getattr(self.app, "database", None)
        if db is not None:
            return [r[0] for r in db.execute(
                "SELECT ledgerseq FROM publishqueue ORDER BY ledgerseq"
            ).fetchall()]
        return sorted(getattr(self, "_mem_queue", {}))

    def _queued_has(self, seq: int) -> Optional[HistoryArchiveState]:
        db = getattr(self.app, "database", None)
        if db is not None:
            row = db.execute(
                "SELECT state FROM publishqueue WHERE ledgerseq = ?",
                (seq,)).fetchone()
            return HistoryArchiveState.from_json(row[0]) if row else None
        return getattr(self, "_mem_queue", {}).get(seq)

    def _dequeue(self, seq: int) -> None:
        db = getattr(self.app, "database", None)
        if db is not None:
            db.execute("DELETE FROM publishqueue WHERE ledgerseq = ?",
                       (seq,))
            db.commit()
        else:
            getattr(self, "_mem_queue", {}).pop(seq, None)

    # -- publishing ----------------------------------------------------------
    def publish_queued_history(self) -> int:
        """Publish every queued checkpoint synchronously-in-order via the
        work scheduler's process path. Returns checkpoints published."""
        n = 0
        for seq in self.publish_queue():
            if self._publish_one(seq):
                self._dequeue(seq)
                self.published_checkpoints += 1
                n += 1
            else:
                self.failed_publishes += 1
                break                # retry next time, keep order
        self._update_publish_status()
        return n

    def _update_publish_status(self) -> None:
        """One rolled-up line about the publish backlog (reference
        HistoryManagerImpl::logAndUpdatePublishStatus:104-122)."""
        from ..util.status_manager import StatusCategory
        sm = getattr(self.app, "status_manager", None)
        if sm is None:
            return
        queue = self.publish_queue()
        if queue:
            sm.set_status_message(
                StatusCategory.HISTORY_PUBLISH,
                "Publishing %d queued checkpoints [%s]" % (
                    len(queue), ", ".join(str(s) for s in queue[:8])))
        else:
            sm.remove_status_message(StatusCategory.HISTORY_PUBLISH)

    def _publish_one(self, checkpoint: int) -> bool:
        has = self._queued_has(checkpoint)
        if has is None:
            return True
        staging = os.path.join(self.publish_queue_dir.path,
                               "%08x" % checkpoint)
        snap = StateSnapshot(self.app, checkpoint, has, staging)
        files = snap.write_all()
        ok_all = True
        for archive in self.writable_archives():
            ok = self._put_snapshot(archive, checkpoint, has, files)
            ok_all = ok_all and ok
            if ok:
                m = getattr(self.app, "metrics", None)
                if m is not None:
                    m.new_meter("history.publish.success").mark()
                log.info("published checkpoint %d to %s", checkpoint,
                         archive.name)
        return ok_all

    def _put_snapshot(self, archive: HistoryArchive, checkpoint: int,
                      has: HistoryArchiveState, files: dict) -> bool:
        for category in ("ledger", "transactions", "results", "scp"):
            src = files[category]
            if not os.path.exists(src):
                continue
            gz = gzip_file(src)
            if not archive.put_file_sync(
                    gz, category_path(category, checkpoint, ".xdr.gz")):
                return False
        for bpath in files["buckets"]:
            hh = os.path.basename(bpath).split("-")[1].split(".")[0]
            gz = gzip_file(bpath)
            if not archive.put_file_sync(gz, bucket_path(hh)):
                return False
        if not archive.put_file_sync(
                files["has"], category_path("history", checkpoint, ".json")):
            return False
        return archive.put_file_sync(files["has"], WELL_KNOWN)
