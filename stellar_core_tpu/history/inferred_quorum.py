"""InferredQuorum: mine quorum sets from published SCP history streams.

Role parity: reference `src/history/InferredQuorum.{h,cpp}` + the
`infer-quorum` CLI subcommand (src/main/CommandLine.cpp:1060-1066): walk a
range of checkpoints' scp-*.xdr streams, harvest every (nodeID → latest
quorum set) binding plus pubkey activity counts, and report the network's
inferred quorum structure.
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, List, Optional, Tuple

from ..crypto.hashing import sha256
from ..crypto.strkey import encode_public_key
from ..herder.pending_envelopes import statement_qset_hash
from ..util.xdrstream import XDRInputFileStream
from ..xdr import SCPHistoryEntry, SCPQuorumSet
from .archive import HistoryArchive, category_path
from .checkpoints import checkpoints_in_range
from .snapshot import gunzip_file


class InferredQuorum:
    def __init__(self) -> None:
        self.qsets: Dict[bytes, SCPQuorumSet] = {}         # qset hash → qset
        self.node_qset: Dict[bytes, bytes] = {}            # node → qset hash
        self.counts: Dict[bytes, int] = {}                 # node → #pledges
        self.latest_seq: Dict[bytes, int] = {}             # node → last slot

    # -- harvesting ----------------------------------------------------------
    def note_entry(self, entry) -> None:
        v0 = entry.value
        for q in v0.quorumSets:
            self.qsets[sha256(q.to_xdr())] = q
        for env in v0.ledgerMessages.messages:
            st = env.statement
            node = st.nodeID.key_bytes
            self.counts[node] = self.counts.get(node, 0) + 1
            if st.slotIndex >= self.latest_seq.get(node, 0):
                self.latest_seq[node] = st.slotIndex
                self.node_qset[node] = statement_qset_hash(st)

    def harvest_stream(self, path: str) -> int:
        n = 0
        with XDRInputFileStream(path) as stream:
            while True:
                entry = stream.read_one(SCPHistoryEntry)
                if entry is None:
                    break
                self.note_entry(entry)
                n += 1
        return n

    def harvest_archive(self, archive: HistoryArchive,
                        first_ledger: int, last_ledger: int,
                        freq: int) -> int:
        """Fetch + gunzip the scp category for every checkpoint in range.
        The range is clamped to the archive head (from the .well-known
        HistoryArchiveState) so an open-ended --last never turns into
        millions of speculative fetches."""
        entries = 0
        with tempfile.TemporaryDirectory(prefix="sct-iq-") as tmp:
            has_path = os.path.join(tmp, "has.json")
            if archive.get_file_sync(
                    ".well-known/stellar-history.json", has_path):
                import json
                with open(has_path) as fh:
                    head = int(json.load(fh).get("currentLedger", 0))
                if head:
                    last_ledger = min(last_ledger, head)
            for cp in checkpoints_in_range(first_ledger, last_ledger, freq):
                remote = category_path("scp", cp, ".xdr.gz")
                local = os.path.join(tmp, "scp-%08x.xdr.gz" % cp)
                if not archive.get_file_sync(remote, local):
                    continue
                entries += self.harvest_stream(gunzip_file(local))
        return entries

    # -- reporting -----------------------------------------------------------
    def get_qset(self, node: bytes) -> Optional[SCPQuorumSet]:
        h = self.node_qset.get(node)
        return self.qsets.get(h) if h is not None else None

    def nodes_by_activity(self) -> List[Tuple[bytes, int]]:
        return sorted(self.counts.items(), key=lambda kv: (-kv[1], kv[0]))

    def to_json(self) -> dict:
        def qset_json(q: SCPQuorumSet) -> dict:
            return {
                "threshold": q.threshold,
                "validators": [encode_public_key(v.key_bytes)
                               for v in q.validators],
                "inner": [qset_json(i) for i in q.innerSets],
            }

        nodes = []
        for node, count in self.nodes_by_activity():
            q = self.get_qset(node)
            nodes.append({
                "node": encode_public_key(node),
                "pledges": count,
                "last_slot": self.latest_seq.get(node, 0),
                "qset": qset_json(q) if q is not None else None,
            })
        return {"node_count": len(nodes), "qset_count": len(self.qsets),
                "nodes": nodes}

    def check_quorum_intersection(self) -> Optional[bool]:
        """Run the quorum-intersection checker over the inferred qset map
        (reference checkQuorumIntersection on an InferredQuorum)."""
        from ..herder.quorum_intersection import QuorumIntersectionChecker
        qmap = {n: self.get_qset(n) for n in self.node_qset}
        if not qmap or any(v is None for v in qmap.values()):
            return None
        return QuorumIntersectionChecker(qmap).network_enjoys_quorum_intersection()
