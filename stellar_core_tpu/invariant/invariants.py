"""Invariants: registered safety predicates checked at apply time.

Role parity: reference `src/invariant/` — InvariantManager
(InvariantManager.h:39-56) + concrete invariants
(ConservationOfLumens.cpp, LedgerEntryIsValid.cpp,
AccountSubEntriesCountIsValid.cpp, LiabilitiesMatchOffers.cpp,
BucketListIsConsistentWithDatabase.cpp). A failing invariant raises
InvariantDoesNotHold, which aborts the node (tests run with all enabled).
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

from ..util.log import get_logger
from ..xdr import LedgerEntryType, LedgerHeader

log = get_logger("Invariant")

Delta = List[Tuple[object, object, object]]  # (key, prev, cur)


class InvariantDoesNotHold(Exception):
    pass


class Invariant:
    name = "abstract"

    def check_on_close(self, delta: Delta, header_prev: LedgerHeader,
                       header_cur: LedgerHeader) -> Optional[str]:
        """Return an error string or None."""
        return None

    def check_on_operation(self, op_frame, delta: Delta,
                           header_prev: LedgerHeader,
                           header_cur: LedgerHeader) -> Optional[str]:
        """Per-operation check over the op's own LedgerTxn delta
        (reference InvariantManager::checkOnOperationApply,
        InvariantManager.h:39-49). Catches compensating-bug pairs that a
        whole-ledger delta would cancel out. Default: reuse the close
        check on the op delta."""
        return self.check_on_close(delta, header_prev, header_cur)

    def check_on_bucket_apply(self, entries, ltx_root, level: int,
                              is_curr: bool) -> Optional[str]:
        """Post-bucket-application consistency (reference
        checkOnBucketApply)."""
        return None


class LedgerEntryIsValid(Invariant):
    name = "LedgerEntryIsValid"

    def check_on_close(self, delta, header_prev, header_cur):
        for key, prev, cur in delta:
            if cur is None:
                continue
            d = cur.data
            if cur.lastModifiedLedgerSeq > header_cur.ledgerSeq:
                return "entry lastModified in the future"
            if d.disc == LedgerEntryType.ACCOUNT:
                a = d.value
                if a.balance < 0:
                    return "account balance negative"
                if a.seqNum < 0:
                    return "account seqnum negative"
                if prev is not None and \
                        prev.data.disc == LedgerEntryType.ACCOUNT and \
                        a.seqNum < prev.data.value.seqNum:
                    return "account seqnum decreased"
                if len(a.signers) > 20:
                    return "too many signers"
                hints = [s.key.to_xdr() for s in a.signers]
                if hints != sorted(hints):
                    return "signers not sorted"
            elif d.disc == LedgerEntryType.TRUSTLINE:
                t = d.value
                if t.balance < 0 or t.limit <= 0 or t.balance > t.limit:
                    return "trustline balance/limit invalid"
            elif d.disc == LedgerEntryType.OFFER:
                o = d.value
                if o.amount <= 0:
                    return "offer amount non-positive"
                if o.price.n <= 0 or o.price.d <= 0:
                    return "offer price invalid"
        return None


class ConservationOfLumens(Invariant):
    name = "ConservationOfLumens"

    def check_on_close(self, delta, header_prev, header_cur):
        d_balance = 0
        for key, prev, cur in delta:
            if prev is not None and \
                    prev.data.disc == LedgerEntryType.ACCOUNT:
                d_balance -= prev.data.value.balance
            if cur is not None and \
                    cur.data.disc == LedgerEntryType.ACCOUNT:
                d_balance += cur.data.value.balance
        d_fee = header_cur.feePool - header_prev.feePool
        d_total = header_cur.totalCoins - header_prev.totalCoins
        if d_balance + d_fee != d_total:
            return ("lumens not conserved: dBalance=%d dFeePool=%d "
                    "dTotal=%d" % (d_balance, d_fee, d_total))
        return None


class AccountSubEntriesCountIsValid(Invariant):
    name = "AccountSubEntriesCountIsValid"

    def check_on_close(self, delta, header_prev, header_cur):
        d_sub: Dict[bytes, int] = {}
        d_declared: Dict[bytes, int] = {}
        for key, prev, cur in delta:
            t = (cur or prev).data.disc
            if t == LedgerEntryType.ACCOUNT:
                acc = (cur or prev).data.value.accountID.key_bytes
                pv = prev.data.value.numSubEntries if prev else 0
                cv = cur.data.value.numSubEntries if cur else 0
                d_declared[acc] = d_declared.get(acc, 0) + cv - pv
                # signers live inside the account entry but count as
                # subentries (reference AccountSubEntriesCountIsValid
                # counts signers.size() alongside owned entries)
                ps = len(prev.data.value.signers) if prev else 0
                cs = len(cur.data.value.signers) if cur else 0
                d_sub[acc] = d_sub.get(acc, 0) + cs - ps
                if cur is None:
                    # merged account must have no subentries
                    if prev.data.value.numSubEntries != ps:
                        return "account removed with subentries"
                    d_declared.pop(acc, None)
                    d_sub.pop(acc, None)
            elif t in (LedgerEntryType.TRUSTLINE, LedgerEntryType.DATA):
                e = (cur or prev).data.value
                acc = e.accountID.key_bytes
                d_sub[acc] = d_sub.get(acc, 0) + \
                    (1 if cur is not None else 0) - \
                    (1 if prev is not None else 0)
            elif t == LedgerEntryType.OFFER:
                e = (cur or prev).data.value
                acc = e.sellerID.key_bytes
                d_sub[acc] = d_sub.get(acc, 0) + \
                    (1 if cur is not None else 0) - \
                    (1 if prev is not None else 0)
        for acc in set(d_sub) | set(d_declared):
            if d_sub.get(acc, 0) != d_declared.get(acc, 0):
                return ("subentry count mismatch for account: "
                        "actual delta %d vs declared %d" %
                        (d_sub.get(acc, 0), d_declared.get(acc, 0)))
        return None


class SequentialLedgers(Invariant):
    name = "SequentialLedgers"

    def check_on_close(self, delta, header_prev, header_cur):
        if header_cur.ledgerSeq != header_prev.ledgerSeq + 1:
            return "ledger seq not sequential"
        return None

    def check_on_operation(self, op_frame, delta, header_prev, header_cur):
        return None  # ops run within one ledger


class LiabilitiesMatchOffers(Invariant):
    """Reference src/invariant/LiabilitiesMatchOffers.cpp: every change in
    an account's/trustline's liabilities must be explained by offer
    changes in the same delta, and liabilities must stay within balance /
    limit bounds."""

    name = "LiabilitiesMatchOffers"

    @staticmethod
    def _liab(entry) -> Tuple[int, int]:
        if entry is None:
            return (0, 0)
        dv = entry.data.value
        if dv.ext.disc == 0:
            return (0, 0)
        li = dv.ext.value.liabilities
        return (li.buying, li.selling)

    def _offer_deltas(self, delta):
        from ..transactions.offer_exchange import offer_liabilities
        from ..xdr import Asset
        d_buying: Dict[tuple, int] = {}
        d_selling: Dict[tuple, int] = {}
        for key, prev, cur in delta:
            if (cur or prev).data.disc != LedgerEntryType.OFFER:
                continue
            for e, sign in ((prev, -1), (cur, +1)):
                if e is None:
                    continue
                o = e.data.value
                bl, sl = offer_liabilities(o.price.n, o.price.d, o.amount)
                seller = o.sellerID.key_bytes
                # issuer side carries no liability (issuer mints/burns)
                if o.buying.is_native or o.sellerID != o.buying.issuer:
                    k = (seller, o.buying.to_xdr())
                    d_buying[k] = d_buying.get(k, 0) + sign * bl
                if o.selling.is_native or o.sellerID != o.selling.issuer:
                    k = (seller, o.selling.to_xdr())
                    d_selling[k] = d_selling.get(k, 0) + sign * sl
        return d_buying, d_selling

    def check_on_operation(self, op_frame, delta, header_prev, header_cur):
        from ..xdr import Asset
        if header_cur.ledgerVersion < 10:
            return None
        d_buying, d_selling = self._offer_deltas(delta)
        native = Asset.native().to_xdr()
        for key, prev, cur in delta:
            t = (cur or prev).data.disc
            if t == LedgerEntryType.ACCOUNT:
                dv = (cur or prev).data.value
                k = (dv.accountID.key_bytes, native)
            elif t == LedgerEntryType.TRUSTLINE:
                dv = (cur or prev).data.value
                k = (dv.accountID.key_bytes, dv.asset.to_xdr())
            else:
                continue
            pb, ps = self._liab(prev)
            cb, cs = self._liab(cur)
            if cb - pb != d_buying.pop(k, 0):
                return ("buying liabilities changed by %d without matching "
                        "offer delta" % (cb - pb))
            if cs - ps != d_selling.pop(k, 0):
                return ("selling liabilities changed by %d without "
                        "matching offer delta" % (cs - ps))
            # bound checks on the new state (reference checkBalanceAndLimit)
            if cur is not None:
                dvc = cur.data.value
                if cb < 0 or cs < 0:
                    return "negative liabilities"
                if t == LedgerEntryType.ACCOUNT:
                    reserve = (2 + dvc.numSubEntries) * header_cur.baseReserve
                    if dvc.balance - reserve < cs:
                        return "selling liabilities exceed available balance"
                    if dvc.balance > (2**63 - 1) - cb:
                        return "buying liabilities exceed INT64 headroom"
                else:
                    if dvc.balance < cs:
                        return "selling liabilities exceed trust balance"
                    if dvc.balance > dvc.limit - cb:
                        return "buying liabilities exceed trust limit"
        for k, v in list(d_buying.items()) + list(d_selling.items()):
            if v != 0:
                return ("offer liability delta %d has no matching "
                        "account/trustline change" % v)
        return None

    def check_on_close(self, delta, header_prev, header_cur):
        return self.check_on_operation(None, delta, header_prev, header_cur)


class BucketListIsConsistentWithDatabase(Invariant):
    """Reference src/invariant/BucketListIsConsistentWithDatabase.cpp:
    after a bucket is applied during catchup, the ledger state must
    contain exactly the bucket's live entries and none of its dead
    keys."""

    name = "BucketListIsConsistentWithDatabase"

    def check_on_bucket_apply(self, entries, ltx_root, level, is_curr):
        from ..bucket.bucket import BucketEntryType
        from ..xdr import ledger_entry_key
        for be in entries:
            if be.type in (BucketEntryType.LIVEENTRY,
                           BucketEntryType.INITENTRY):
                key = ledger_entry_key(be.entry)
                got = ltx_root.get_entry(key)
                if got is None:
                    return "live bucket entry missing from ledger state"
                if got.to_xdr() != be.entry.to_xdr():
                    return "ledger state disagrees with applied bucket entry"
            elif be.type == BucketEntryType.DEADENTRY:
                if ltx_root.get_entry(be.key) is not None:
                    return "dead bucket key still present in ledger state"
        return None


ALL_INVARIANTS = [LedgerEntryIsValid, ConservationOfLumens,
                  AccountSubEntriesCountIsValid, SequentialLedgers,
                  LiabilitiesMatchOffers,
                  BucketListIsConsistentWithDatabase]


class InvariantManager:
    """Registry + enforcement (reference InvariantManagerImpl.cpp:72-143)."""

    def __init__(self, metrics=None) -> None:
        self._registered: Dict[str, Invariant] = {}
        self._enabled: List[Invariant] = []
        self._metrics = metrics
        for cls in ALL_INVARIANTS:
            self.register(cls())

    def register(self, inv: Invariant) -> None:
        assert inv.name not in self._registered
        self._registered[inv.name] = inv

    def enable(self, pattern: str) -> None:
        rx = re.compile(pattern)
        for name, inv in self._registered.items():
            if rx.fullmatch(name) and inv not in self._enabled:
                self._enabled.append(inv)

    def enabled_names(self) -> List[str]:
        return [i.name for i in self._enabled]

    def check_on_ledger_close(self, delta, header_prev, header_cur) -> None:
        for inv in self._enabled:
            err = inv.check_on_close(delta, header_prev, header_cur)
            if err is not None:
                msg = "invariant %s violated: %s" % (inv.name, err)
                log.error(msg)
                raise InvariantDoesNotHold(msg)
