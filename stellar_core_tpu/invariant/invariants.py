"""Invariants: registered safety predicates checked at apply time.

Role parity: reference `src/invariant/` — InvariantManager
(InvariantManager.h:39-56) + concrete invariants
(ConservationOfLumens.cpp, LedgerEntryIsValid.cpp,
AccountSubEntriesCountIsValid.cpp, LiabilitiesMatchOffers.cpp,
BucketListIsConsistentWithDatabase.cpp). A failing invariant raises
InvariantDoesNotHold, which aborts the node (tests run with all enabled).
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

from ..util.log import get_logger
from ..xdr import LedgerEntryType, LedgerHeader

log = get_logger("Invariant")

Delta = List[Tuple[object, object, object]]  # (key, prev, cur)


class InvariantDoesNotHold(Exception):
    pass


class Invariant:
    name = "abstract"

    def check_on_close(self, delta: Delta, header_prev: LedgerHeader,
                       header_cur: LedgerHeader) -> Optional[str]:
        """Return an error string or None."""
        return None


class LedgerEntryIsValid(Invariant):
    name = "LedgerEntryIsValid"

    def check_on_close(self, delta, header_prev, header_cur):
        for key, prev, cur in delta:
            if cur is None:
                continue
            d = cur.data
            if cur.lastModifiedLedgerSeq > header_cur.ledgerSeq:
                return "entry lastModified in the future"
            if d.disc == LedgerEntryType.ACCOUNT:
                a = d.value
                if a.balance < 0:
                    return "account balance negative"
                if a.seqNum < 0:
                    return "account seqnum negative"
                if prev is not None and \
                        prev.data.disc == LedgerEntryType.ACCOUNT and \
                        a.seqNum < prev.data.value.seqNum:
                    return "account seqnum decreased"
                if len(a.signers) > 20:
                    return "too many signers"
                hints = [s.key.to_xdr() for s in a.signers]
                if hints != sorted(hints):
                    return "signers not sorted"
            elif d.disc == LedgerEntryType.TRUSTLINE:
                t = d.value
                if t.balance < 0 or t.limit <= 0 or t.balance > t.limit:
                    return "trustline balance/limit invalid"
            elif d.disc == LedgerEntryType.OFFER:
                o = d.value
                if o.amount <= 0:
                    return "offer amount non-positive"
                if o.price.n <= 0 or o.price.d <= 0:
                    return "offer price invalid"
        return None


class ConservationOfLumens(Invariant):
    name = "ConservationOfLumens"

    def check_on_close(self, delta, header_prev, header_cur):
        d_balance = 0
        for key, prev, cur in delta:
            if prev is not None and \
                    prev.data.disc == LedgerEntryType.ACCOUNT:
                d_balance -= prev.data.value.balance
            if cur is not None and \
                    cur.data.disc == LedgerEntryType.ACCOUNT:
                d_balance += cur.data.value.balance
        d_fee = header_cur.feePool - header_prev.feePool
        d_total = header_cur.totalCoins - header_prev.totalCoins
        if d_balance + d_fee != d_total:
            return ("lumens not conserved: dBalance=%d dFeePool=%d "
                    "dTotal=%d" % (d_balance, d_fee, d_total))
        return None


class AccountSubEntriesCountIsValid(Invariant):
    name = "AccountSubEntriesCountIsValid"

    def check_on_close(self, delta, header_prev, header_cur):
        d_sub: Dict[bytes, int] = {}
        d_declared: Dict[bytes, int] = {}
        for key, prev, cur in delta:
            t = (cur or prev).data.disc
            if t == LedgerEntryType.ACCOUNT:
                acc = (cur or prev).data.value.accountID.key_bytes
                pv = prev.data.value.numSubEntries if prev else 0
                cv = cur.data.value.numSubEntries if cur else 0
                d_declared[acc] = d_declared.get(acc, 0) + cv - pv
                if cur is None:
                    # merged account must have no subentries
                    if prev.data.value.numSubEntries != 0:
                        return "account removed with subentries"
                    d_declared.pop(acc, None)
            elif t in (LedgerEntryType.TRUSTLINE, LedgerEntryType.DATA):
                e = (cur or prev).data.value
                acc = e.accountID.key_bytes
                d_sub[acc] = d_sub.get(acc, 0) + \
                    (1 if cur is not None else 0) - \
                    (1 if prev is not None else 0)
            elif t == LedgerEntryType.OFFER:
                e = (cur or prev).data.value
                acc = e.sellerID.key_bytes
                d_sub[acc] = d_sub.get(acc, 0) + \
                    (1 if cur is not None else 0) - \
                    (1 if prev is not None else 0)
        for acc in set(d_sub) | set(d_declared):
            if d_sub.get(acc, 0) != d_declared.get(acc, 0):
                return ("subentry count mismatch for account: "
                        "actual delta %d vs declared %d" %
                        (d_sub.get(acc, 0), d_declared.get(acc, 0)))
        return None


class SequentialLedgers(Invariant):
    name = "SequentialLedgers"

    def check_on_close(self, delta, header_prev, header_cur):
        if header_cur.ledgerSeq != header_prev.ledgerSeq + 1:
            return "ledger seq not sequential"
        return None


ALL_INVARIANTS = [LedgerEntryIsValid, ConservationOfLumens,
                  AccountSubEntriesCountIsValid, SequentialLedgers]


class InvariantManager:
    """Registry + enforcement (reference InvariantManagerImpl.cpp:72-143)."""

    def __init__(self, metrics=None) -> None:
        self._registered: Dict[str, Invariant] = {}
        self._enabled: List[Invariant] = []
        self._metrics = metrics
        for cls in ALL_INVARIANTS:
            self.register(cls())

    def register(self, inv: Invariant) -> None:
        assert inv.name not in self._registered
        self._registered[inv.name] = inv

    def enable(self, pattern: str) -> None:
        rx = re.compile(pattern)
        for name, inv in self._registered.items():
            if rx.fullmatch(name) and inv not in self._enabled:
                self._enabled.append(inv)

    def enabled_names(self) -> List[str]:
        return [i.name for i in self._enabled]

    def check_on_ledger_close(self, delta, header_prev, header_cur) -> None:
        for inv in self._enabled:
            err = inv.check_on_close(delta, header_prev, header_cur)
            if err is not None:
                msg = "invariant %s violated: %s" % (inv.name, err)
                log.error(msg)
                raise InvariantDoesNotHold(msg)
