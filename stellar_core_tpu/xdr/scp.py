"""SCP wire types: statements, envelopes, quorum sets.

Role parity: reference `src/xdr/Stellar-SCP.x`.
"""

from __future__ import annotations

from .basic import Hash, NodeID, Signature, Value
from .codec import Uint32, Uint64, VarArray, XdrStruct, XdrUnion


class SCPBallot(XdrStruct):
    xdr_fields = [("counter", Uint32), ("value", Value)]


class SCPStatementType:
    SCP_ST_PREPARE = 0
    SCP_ST_CONFIRM = 1
    SCP_ST_EXTERNALIZE = 2
    SCP_ST_NOMINATE = 3


class SCPNomination(XdrStruct):
    xdr_fields = [
        ("quorumSetHash", Hash),
        ("votes", VarArray(Value)),
        ("accepted", VarArray(Value)),
    ]


class SCPPrepare(XdrStruct):
    from .codec import OptionalT as _Opt
    xdr_fields = [
        ("quorumSetHash", Hash),
        ("ballot", SCPBallot),
        ("prepared", _Opt(SCPBallot)),
        ("preparedPrime", _Opt(SCPBallot)),
        ("nC", Uint32),
        ("nH", Uint32),
    ]


class SCPConfirm(XdrStruct):
    xdr_fields = [
        ("ballot", SCPBallot),
        ("nPrepared", Uint32),
        ("nCommit", Uint32),
        ("nH", Uint32),
        ("quorumSetHash", Hash),
    ]


class SCPExternalize(XdrStruct):
    xdr_fields = [
        ("commit", SCPBallot),
        ("nH", Uint32),
        ("commitQuorumSetHash", Hash),
    ]


class SCPPledges(XdrUnion):
    xdr_arms = {
        SCPStatementType.SCP_ST_PREPARE: ("prepare", SCPPrepare),
        SCPStatementType.SCP_ST_CONFIRM: ("confirm", SCPConfirm),
        SCPStatementType.SCP_ST_EXTERNALIZE: ("externalize", SCPExternalize),
        SCPStatementType.SCP_ST_NOMINATE: ("nominate", SCPNomination),
    }


class SCPStatement(XdrStruct):
    xdr_fields = [
        ("nodeID", NodeID),
        ("slotIndex", Uint64),
        ("pledges", SCPPledges),
    ]


class SCPEnvelope(XdrStruct):
    xdr_fields = [("statement", SCPStatement), ("signature", Signature)]


class SCPQuorumSet(XdrStruct):
    """Recursive quorum set: threshold over validators + inner sets."""
    xdr_fields = []  # patched below for self-reference


SCPQuorumSet.xdr_fields = [
    ("threshold", Uint32),
    ("validators", VarArray(NodeID)),
    ("innerSets", VarArray(SCPQuorumSet)),
]


class SCPHistoryEntryV0(XdrStruct):
    xdr_fields = [
        ("quorumSets", VarArray(SCPQuorumSet)),
        ("ledgerMessages", XdrStruct),  # patched below
    ]


class LedgerSCPMessages(XdrStruct):
    xdr_fields = [("ledgerSeq", Uint32), ("messages", VarArray(SCPEnvelope))]


SCPHistoryEntryV0.xdr_fields = [
    ("quorumSets", VarArray(SCPQuorumSet)),
    ("ledgerMessages", LedgerSCPMessages),
]


class SCPHistoryEntry(XdrUnion):
    xdr_arms = {0: ("v0", SCPHistoryEntryV0)}
