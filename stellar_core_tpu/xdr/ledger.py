"""Ledger header, close values, upgrades, history entries, meta.

Role parity: reference `src/xdr/Stellar-ledger.x`.
"""

from __future__ import annotations

from .basic import Hash, NodeID, Signature, UpgradeType, Value
from .ledger_entries import LedgerEntry, LedgerKey, _Ext
from .transaction import (
    TransactionEnvelope, TransactionResultPair, OperationResult,
)
from .codec import (
    FixedArray, Int64, Uint32, Uint64, VarArray, XdrStruct, XdrUnion,
)


class LedgerCloseValueSignature(XdrStruct):
    xdr_fields = [("nodeID", NodeID), ("signature", Signature)]


class StellarValueExt(XdrUnion):
    STELLAR_VALUE_BASIC = 0
    STELLAR_VALUE_SIGNED = 1
    xdr_arms = {
        0: ("basic", None),
        1: ("lcValueSignature", LedgerCloseValueSignature),
    }


class StellarValue(XdrStruct):
    """The value SCP agrees on per slot: (txset hash, closeTime, upgrades).

    Reference: Stellar-ledger.x StellarValue; built in
    HerderImpl::triggerNextLedger (/root/reference/src/herder/HerderImpl.cpp:743).
    """
    MAX_UPGRADES = 6
    xdr_fields = [
        ("txSetHash", Hash),
        ("closeTime", Uint64),
        ("upgrades", VarArray(UpgradeType, 6)),
        ("ext", StellarValueExt),
    ]


class LedgerHeader(XdrStruct):
    xdr_fields = [
        ("ledgerVersion", Uint32),
        ("previousLedgerHash", Hash),
        ("scpValue", StellarValue),
        ("txSetResultHash", Hash),
        ("bucketListHash", Hash),
        ("ledgerSeq", Uint32),
        ("totalCoins", Int64),
        ("feePool", Int64),
        ("inflationSeq", Uint32),
        ("idPool", Uint64),
        ("baseFee", Uint32),
        ("baseReserve", Uint32),
        ("maxTxSetSize", Uint32),
        ("skipList", FixedArray(Hash, 4)),
        ("ext", _Ext),
    ]


class LedgerUpgradeType:
    LEDGER_UPGRADE_VERSION = 1
    LEDGER_UPGRADE_BASE_FEE = 2
    LEDGER_UPGRADE_MAX_TX_SET_SIZE = 3
    LEDGER_UPGRADE_BASE_RESERVE = 4


class LedgerUpgrade(XdrUnion):
    xdr_arms = {
        LedgerUpgradeType.LEDGER_UPGRADE_VERSION: ("newLedgerVersion", Uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE: ("newBaseFee", Uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            ("newMaxTxSetSize", Uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE: ("newBaseReserve", Uint32),
    }


class TransactionSet(XdrStruct):
    xdr_fields = [
        ("previousLedgerHash", Hash),
        ("txs", VarArray(TransactionEnvelope)),
    ]


class LedgerHeaderHistoryEntry(XdrStruct):
    xdr_fields = [("hash", Hash), ("header", LedgerHeader), ("ext", _Ext)]


class TransactionHistoryEntry(XdrStruct):
    xdr_fields = [("ledgerSeq", Uint32), ("txSet", TransactionSet),
                  ("ext", _Ext)]


class TransactionHistoryResultEntry(XdrStruct):
    from .transaction import TransactionResultSet as _TRS
    xdr_fields = [("ledgerSeq", Uint32), ("txResultSet", _TRS), ("ext", _Ext)]


# --- Ledger entry change meta ---------------------------------------------

class LedgerEntryChangeType:
    LEDGER_ENTRY_CREATED = 0
    LEDGER_ENTRY_UPDATED = 1
    LEDGER_ENTRY_REMOVED = 2
    LEDGER_ENTRY_STATE = 3


class LedgerEntryChange(XdrUnion):
    xdr_arms = {
        LedgerEntryChangeType.LEDGER_ENTRY_CREATED: ("created", LedgerEntry),
        LedgerEntryChangeType.LEDGER_ENTRY_UPDATED: ("updated", LedgerEntry),
        LedgerEntryChangeType.LEDGER_ENTRY_REMOVED: ("removed", LedgerKey),
        LedgerEntryChangeType.LEDGER_ENTRY_STATE: ("state", LedgerEntry),
    }


LedgerEntryChanges = VarArray(LedgerEntryChange)


class OperationMeta(XdrStruct):
    xdr_fields = [("changes", LedgerEntryChanges)]


class TransactionMetaV1(XdrStruct):
    xdr_fields = [("txChanges", LedgerEntryChanges),
                  ("operations", VarArray(OperationMeta))]


class TransactionMeta(XdrUnion):
    xdr_arms = {1: ("v1", TransactionMetaV1)}


# --- Ledger close meta (reference src/xdr/Stellar-ledger.x:282-320) --------
# The full per-close record streamed to downstream consumers (Horizon-style
# ingestion) via METADATA_OUTPUT_STREAM.

class TransactionResultMeta(XdrStruct):
    xdr_fields = [
        ("result", TransactionResultPair),
        ("feeProcessing", LedgerEntryChanges),
        ("txApplyProcessing", TransactionMeta),
    ]


class UpgradeEntryMeta(XdrStruct):
    xdr_fields = [
        ("upgrade", LedgerUpgrade),
        ("changes", LedgerEntryChanges),
    ]


class LedgerCloseMetaV0(XdrStruct):
    from .scp import SCPHistoryEntry as _SHE
    xdr_fields = [
        ("ledgerHeader", LedgerHeaderHistoryEntry),
        ("txSet", TransactionSet),
        # in apply order, one entry per tx: result + fee-processing
        # changes + full apply meta
        ("txProcessing", VarArray(TransactionResultMeta)),
        ("upgradesProcessing", VarArray(UpgradeEntryMeta)),
        ("scpInfo", VarArray(_SHE)),
    ]


class LedgerCloseMeta(XdrUnion):
    xdr_arms = {0: ("v0", LedgerCloseMetaV0)}

    @classmethod
    def v0(cls, value) -> "LedgerCloseMeta":
        return cls(0, value)


# --- Bucket entries (reference src/xdr/Stellar-ledger.x:148-182) -----------

class BucketEntryType:
    """METAENTRY sorts first in buckets; INITENTRY = created (protocol>=11),
    LIVEENTRY = updated, DEADENTRY = tombstone."""
    METAENTRY = -1
    LIVEENTRY = 0
    DEADENTRY = 1
    INITENTRY = 2


class BucketMetadata(XdrStruct):
    """First entry of every bucket at protocol >= 11; records the protocol
    version used to create/merge the bucket."""
    xdr_fields = [("ledgerVersion", Uint32), ("ext", _Ext)]


class BucketEntry(XdrUnion):
    xdr_arms = {
        BucketEntryType.LIVEENTRY: ("liveEntry", LedgerEntry),
        BucketEntryType.INITENTRY: ("liveEntry", LedgerEntry),
        BucketEntryType.DEADENTRY: ("deadEntry", LedgerKey),
        BucketEntryType.METAENTRY: ("metaEntry", BucketMetadata),
    }

    @classmethod
    def live(cls, e: LedgerEntry) -> "BucketEntry":
        return cls(BucketEntryType.LIVEENTRY, e)

    @classmethod
    def init(cls, e: LedgerEntry) -> "BucketEntry":
        return cls(BucketEntryType.INITENTRY, e)

    @classmethod
    def dead(cls, k: LedgerKey) -> "BucketEntry":
        return cls(BucketEntryType.DEADENTRY, k)

    @classmethod
    def meta(cls, ledger_version: int) -> "BucketEntry":
        return cls(BucketEntryType.METAENTRY,
                   BucketMetadata(ledgerVersion=ledger_version,
                                  ext=_Ext.v0()))
