"""XDR layer: canonical wire/hash format and the full message vocabulary.

Role parity: reference `src/xdr/*.x` + xdrpp codegen (layer 1 in SURVEY.md §1).
"""

from .codec import (
    Bool, EnumT, FixedArray, Int32, Int64, Opaque, OptionalT, Packer,
    Uint32, Uint64, Unpacker, VarArray, VarOpaque, XdrError, XdrString,
    XdrStruct, XdrUnion, xdr_bytes, xdr_from,
)
from .basic import (
    AccountID, CryptoKeyType, Curve25519Public, Curve25519Secret,
    DecoratedSignature, EnvelopeType, Hash, HmacSha256Key, HmacSha256Mac,
    MuxedAccount, MuxedAccountMed25519, NodeID, PublicKey, PublicKeyType,
    Signature, SignatureHint, SignerKey, SignerKeyType, String32, String64,
    DataValue, Uint256, UpgradeType, Value,
)
from .ledger_entries import (
    AccountEntry, AccountEntryExt, AccountEntryExtensionV1, AccountFlags,
    Asset, AssetAlphaNum4, AssetAlphaNum12,
    AssetType, DataEntry, LedgerEntry, LedgerEntryData, LedgerEntryType,
    LedgerKey, LedgerKeyAccount, LedgerKeyData, LedgerKeyOffer,
    LedgerKeyTrustLine, Liabilities, OfferEntry, OfferEntryFlags, Price,
    SequenceNumber, Signer, TrustLineEntry, TrustLineEntryExt,
    TrustLineEntryExtensionV1, TrustLineFlags, ledger_entry_key,
    ledger_key_sort_key, _Ext,
)
from .transaction import (
    AllowTrustAsset, AllowTrustOp, BumpSequenceOp, ChangeTrustOp,
    ClaimOfferAtom, CreateAccountOp, CreatePassiveSellOfferOp,
    FeeBumpTransaction, FeeBumpTransactionEnvelope, InflationPayout,
    ManageBuyOfferOp, ManageDataOp, ManageOfferSuccessResult,
    ManageOfferSuccessResultOffer, ManageSellOfferOp, MAX_OPS_PER_TX, Memo,
    MemoType, Operation, OperationBody, OperationInner, OperationResult,
    OperationResultCode, OperationType, PathPaymentStrictReceiveOp,
    PathPaymentStrictSendOp, PathPaymentSuccess, PaymentOp, SetOptionsOp,
    SimplePaymentResult, TimeBounds, Transaction, TransactionEnvelope,
    TransactionResult, TransactionResultCode, TransactionResultPair,
    TransactionResultSet, TransactionSignaturePayload, TransactionV1Envelope,
    InnerTransactionResultPair,
    CreateAccountResult, PaymentResult, PathPaymentStrictReceiveResult,
    PathPaymentStrictSendResult, ManageSellOfferResult, ManageBuyOfferResult,
    SetOptionsResult, ChangeTrustResult, AllowTrustResult, AccountMergeResult,
    InflationResult, ManageDataResult, BumpSequenceResult,
)
from .ledger import (
    BucketEntry, BucketEntryType, BucketMetadata,
    LedgerCloseMeta, LedgerCloseMetaV0,
    LedgerCloseValueSignature, LedgerEntryChange, LedgerEntryChangeType,
    LedgerEntryChanges, LedgerHeader, LedgerHeaderHistoryEntry, LedgerUpgrade,
    LedgerUpgradeType, OperationMeta, StellarValue, StellarValueExt,
    TransactionHistoryEntry, TransactionHistoryResultEntry, TransactionMeta,
    TransactionMetaV1, TransactionResultMeta, TransactionSet,
    UpgradeEntryMeta,
)
from .scp import (
    LedgerSCPMessages, SCPBallot, SCPEnvelope, SCPHistoryEntry,
    SCPHistoryEntryV0, SCPNomination, SCPPledges, SCPPrepare, SCPConfirm,
    SCPExternalize, SCPQuorumSet, SCPStatement, SCPStatementType,
)
from .overlay import (
    Auth, AuthCert, AuthenticatedMessage, AuthenticatedMessageV0, DontHave,
    Error, ErrorCode, Hello, IPAddr, MessageType, PeerAddress, PeerStats,
    SignedSurveyRequestMessage, SignedSurveyResponseMessage,
    StellarMessage, SurveyMessageCommandType, SurveyRequestMessage,
    SurveyResponseMessage, TopologyResponseBody, EncryptedBody,
)
