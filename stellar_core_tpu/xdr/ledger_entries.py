"""Ledger entry types: accounts, trustlines, offers, data.

Role parity: reference `src/xdr/Stellar-ledger-entries.x`.
"""

from __future__ import annotations

from .basic import AccountID, Hash, String32, String64, DataValue, SignerKey
from .codec import (
    Int32, Int64, Opaque, OptionalT, Uint32, Uint64, VarArray, XdrStruct,
    XdrUnion, XdrError,
)


class AssetType:
    ASSET_TYPE_NATIVE = 0
    ASSET_TYPE_CREDIT_ALPHANUM4 = 1
    ASSET_TYPE_CREDIT_ALPHANUM12 = 2


class AssetAlphaNum4(XdrStruct):
    xdr_fields = [("assetCode", Opaque(4)), ("issuer", AccountID)]


class AssetAlphaNum12(XdrStruct):
    xdr_fields = [("assetCode", Opaque(12)), ("issuer", AccountID)]


class Asset(XdrUnion):
    xdr_arms = {
        AssetType.ASSET_TYPE_NATIVE: ("native", None),
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AssetAlphaNum4),
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AssetAlphaNum12),
    }

    @classmethod
    def native(cls) -> "Asset":
        return cls(AssetType.ASSET_TYPE_NATIVE)

    @classmethod
    def credit(cls, code: str, issuer: AccountID) -> "Asset":
        raw = code.encode("ascii")
        if not 1 <= len(raw) <= 12:
            raise XdrError("bad asset code %r" % code)
        if len(raw) <= 4:
            return cls(AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
                       AssetAlphaNum4(assetCode=raw.ljust(4, b"\x00"),
                                      issuer=issuer))
        return cls(AssetType.ASSET_TYPE_CREDIT_ALPHANUM12,
                   AssetAlphaNum12(assetCode=raw.ljust(12, b"\x00"),
                                   issuer=issuer))

    @property
    def is_native(self) -> bool:
        return self.disc == AssetType.ASSET_TYPE_NATIVE

    @property
    def issuer(self):
        return None if self.is_native else self.value.issuer

    @property
    def code(self) -> str:
        if self.is_native:
            return "XLM"
        return self.value.assetCode.rstrip(b"\x00").decode("ascii", "replace")


class Price(XdrStruct):
    xdr_fields = [("n", Int32), ("d", Int32)]


Thresholds = Opaque(4)
SequenceNumber = Int64


class Signer(XdrStruct):
    xdr_fields = [("key", SignerKey), ("weight", Uint32)]


class AccountFlags:
    AUTH_REQUIRED_FLAG = 0x1
    AUTH_REVOCABLE_FLAG = 0x2
    AUTH_IMMUTABLE_FLAG = 0x4
    MASK_ACCOUNT_FLAGS = 0x7


class _Ext(XdrUnion):
    """Common empty-v0 extension point."""
    xdr_arms = {0: ("v0", None)}

    @classmethod
    def v0(cls) -> "_Ext":
        return cls(0)


class Liabilities(XdrStruct):
    """Protocol >= 10 balance encumbrance (reference
    Stellar-ledger-entries.x Liabilities): `buying` reserves room below
    the balance/limit ceiling, `selling` reserves balance above the
    floor — both maintained by open offers."""
    xdr_fields = [("buying", Int64), ("selling", Int64)]


class AccountEntryExtensionV1(XdrStruct):
    xdr_fields = [("liabilities", Liabilities), ("ext", _Ext)]


class AccountEntryExt(XdrUnion):
    xdr_arms = {0: ("v0", None), 1: ("v1", AccountEntryExtensionV1)}

    @classmethod
    def v0(cls) -> "AccountEntryExt":
        return cls(0)


class TrustLineEntryExtensionV1(XdrStruct):
    xdr_fields = [("liabilities", Liabilities), ("ext", _Ext)]


class TrustLineEntryExt(XdrUnion):
    xdr_arms = {0: ("v0", None), 1: ("v1", TrustLineEntryExtensionV1)}

    @classmethod
    def v0(cls) -> "TrustLineEntryExt":
        return cls(0)


class AccountEntry(XdrStruct):
    MAX_SIGNERS = 20
    xdr_fields = [
        ("accountID", AccountID),
        ("balance", Int64),
        ("seqNum", SequenceNumber),
        ("numSubEntries", Uint32),
        ("inflationDest", OptionalT(AccountID)),
        ("flags", Uint32),
        ("homeDomain", String32),
        ("thresholds", Thresholds),
        ("signers", VarArray(Signer, 20)),
        ("ext", AccountEntryExt),
    ]


class TrustLineFlags:
    AUTHORIZED_FLAG = 1
    # protocol 13 (CAP-0018): may keep existing offers/liabilities but
    # not send/receive payments or post new offers
    AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG = 2
    # either auth level — keeps/releases/executes EXISTING liabilities
    AUTH_LEVELS_MASK = 1 | 2
    MASK_TRUSTLINE_FLAGS = 1
    MASK_TRUSTLINE_FLAGS_V13 = 3


class TrustLineEntry(XdrStruct):
    xdr_fields = [
        ("accountID", AccountID),
        ("asset", Asset),
        ("balance", Int64),
        ("limit", Int64),
        ("flags", Uint32),
        ("ext", TrustLineEntryExt),
    ]


class OfferEntryFlags:
    PASSIVE_FLAG = 1


class OfferEntry(XdrStruct):
    xdr_fields = [
        ("sellerID", AccountID),
        ("offerID", Int64),
        ("selling", Asset),
        ("buying", Asset),
        ("amount", Int64),
        ("price", Price),
        ("flags", Uint32),
        ("ext", _Ext),
    ]


class DataEntry(XdrStruct):
    xdr_fields = [
        ("accountID", AccountID),
        ("dataName", String64),
        ("dataValue", DataValue),
        ("ext", _Ext),
    ]


class LedgerEntryType:
    ACCOUNT = 0
    TRUSTLINE = 1
    OFFER = 2
    DATA = 3


class LedgerEntryData(XdrUnion):
    xdr_arms = {
        LedgerEntryType.ACCOUNT: ("account", AccountEntry),
        LedgerEntryType.TRUSTLINE: ("trustLine", TrustLineEntry),
        LedgerEntryType.OFFER: ("offer", OfferEntry),
        LedgerEntryType.DATA: ("data", DataEntry),
    }


class LedgerEntry(XdrStruct):
    xdr_fields = [
        ("lastModifiedLedgerSeq", Uint32),
        ("data", LedgerEntryData),
        ("ext", _Ext),
    ]


# --- LedgerKey -------------------------------------------------------------

class LedgerKeyAccount(XdrStruct):
    xdr_fields = [("accountID", AccountID)]


class LedgerKeyTrustLine(XdrStruct):
    xdr_fields = [("accountID", AccountID), ("asset", Asset)]


class LedgerKeyOffer(XdrStruct):
    xdr_fields = [("sellerID", AccountID), ("offerID", Int64)]


class LedgerKeyData(XdrStruct):
    xdr_fields = [("accountID", AccountID), ("dataName", String64)]


class LedgerKey(XdrUnion):
    xdr_arms = {
        LedgerEntryType.ACCOUNT: ("account", LedgerKeyAccount),
        LedgerEntryType.TRUSTLINE: ("trustLine", LedgerKeyTrustLine),
        LedgerEntryType.OFFER: ("offer", LedgerKeyOffer),
        LedgerEntryType.DATA: ("data", LedgerKeyData),
    }

    @classmethod
    def account(cls, acc: AccountID) -> "LedgerKey":
        return cls(LedgerEntryType.ACCOUNT, LedgerKeyAccount(accountID=acc))

    @classmethod
    def trustline(cls, acc: AccountID, asset: Asset) -> "LedgerKey":
        return cls(LedgerEntryType.TRUSTLINE,
                   LedgerKeyTrustLine(accountID=acc, asset=asset))

    @classmethod
    def offer(cls, seller: AccountID, offer_id: int) -> "LedgerKey":
        return cls(LedgerEntryType.OFFER,
                   LedgerKeyOffer(sellerID=seller, offerID=offer_id))

    @classmethod
    def data(cls, acc: AccountID, name: str) -> "LedgerKey":
        return cls(LedgerEntryType.DATA,
                   LedgerKeyData(accountID=acc, dataName=name))


def ledger_entry_key(entry: LedgerEntry) -> LedgerKey:
    """The identity key of an entry (reference: LedgerEntryKey in
    src/ledger/LedgerHashUtils.h role)."""
    d = entry.data
    t = d.disc
    if t == LedgerEntryType.ACCOUNT:
        return LedgerKey.account(d.value.accountID)
    if t == LedgerEntryType.TRUSTLINE:
        return LedgerKey.trustline(d.value.accountID, d.value.asset)
    if t == LedgerEntryType.OFFER:
        return LedgerKey.offer(d.value.sellerID, d.value.offerID)
    if t == LedgerEntryType.DATA:
        return LedgerKey.data(d.value.accountID, d.value.dataName)
    raise XdrError("bad entry type %d" % t)


def ledger_key_sort_key(key: LedgerKey):
    """Total order on ledger-entry identities matching the reference's
    field-wise LedgerEntryIdCmp (src/bucket/LedgerCmp.h:27-87): type first,
    then the identifying fields. dataName compares as a raw byte string
    (C++ std::string order), NOT as XDR (which is length-prefixed)."""
    t = key.disc
    v = key.value
    if t == LedgerEntryType.ACCOUNT:
        return (t, v.accountID.to_xdr())
    if t == LedgerEntryType.TRUSTLINE:
        return (t, v.accountID.to_xdr(), v.asset.to_xdr())
    if t == LedgerEntryType.OFFER:
        return (t, v.sellerID.to_xdr(), v.offerID)
    if t == LedgerEntryType.DATA:
        name = v.dataName
        if isinstance(name, str):
            name = name.encode()
        return (t, v.accountID.to_xdr(), name)
    raise XdrError("bad key type %d" % t)
