"""Basic XDR types shared by every layer.

Role parity: reference `src/xdr/Stellar-types.x` (PublicKey, SignerKey,
Signature, Hash, NodeID, HMAC/Curve25519 wrappers).
"""

from __future__ import annotations

from .codec import (
    EnumT, FixedArray, Opaque, OptionalT, Uint32, Uint64, Int32, Int64,
    VarArray, VarOpaque, XdrString, XdrStruct, XdrUnion,
)

Hash = Opaque(32)
Uint256 = Opaque(32)
Signature = VarOpaque(64)
SignatureHint = Opaque(4)
Curve25519Public = Opaque(32)
Curve25519Secret = Opaque(32)
HmacSha256Key = Opaque(32)
HmacSha256Mac = Opaque(32)


class CryptoKeyType:
    KEY_TYPE_ED25519 = 0
    KEY_TYPE_PRE_AUTH_TX = 1
    KEY_TYPE_HASH_X = 2
    KEY_TYPE_MUXED_ED25519 = 0x100


class PublicKeyType:
    PUBLIC_KEY_TYPE_ED25519 = 0


class SignerKeyType:
    SIGNER_KEY_TYPE_ED25519 = 0
    SIGNER_KEY_TYPE_PRE_AUTH_TX = 1
    SIGNER_KEY_TYPE_HASH_X = 2


class PublicKey(XdrUnion):
    xdr_arms = {PublicKeyType.PUBLIC_KEY_TYPE_ED25519: ("ed25519", Uint256)}

    @classmethod
    def ed25519(cls, raw32: bytes) -> "PublicKey":
        return cls(PublicKeyType.PUBLIC_KEY_TYPE_ED25519, raw32)

    @property
    def key_bytes(self) -> bytes:
        return self.value


# Node identity and account identity are both ed25519 public keys.
NodeID = PublicKey
AccountID = PublicKey


class SignerKey(XdrUnion):
    xdr_arms = {
        SignerKeyType.SIGNER_KEY_TYPE_ED25519: ("ed25519", Uint256),
        SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX: ("preAuthTx", Uint256),
        SignerKeyType.SIGNER_KEY_TYPE_HASH_X: ("hashX", Uint256),
    }

    @classmethod
    def ed25519(cls, raw32: bytes) -> "SignerKey":
        return cls(SignerKeyType.SIGNER_KEY_TYPE_ED25519, raw32)

    @classmethod
    def pre_auth_tx(cls, h: bytes) -> "SignerKey":
        return cls(SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX, h)

    @classmethod
    def hash_x(cls, h: bytes) -> "SignerKey":
        return cls(SignerKeyType.SIGNER_KEY_TYPE_HASH_X, h)


class MuxedAccount(XdrUnion):
    """Account reference in transactions; may carry a 64-bit sub-account id."""

    xdr_arms = {
        CryptoKeyType.KEY_TYPE_ED25519: ("ed25519", Uint256),
        CryptoKeyType.KEY_TYPE_MUXED_ED25519: ("med25519", None),  # patched below
    }

    @classmethod
    def from_account_id(cls, acc: PublicKey) -> "MuxedAccount":
        return cls(CryptoKeyType.KEY_TYPE_ED25519, acc.key_bytes)

    @property
    def account_id(self) -> PublicKey:
        if self.disc == CryptoKeyType.KEY_TYPE_ED25519:
            return PublicKey.ed25519(self.value)
        return PublicKey.ed25519(self.value.ed25519)


class MuxedAccountMed25519(XdrStruct):
    xdr_fields = [("id", Uint64), ("ed25519", Uint256)]


MuxedAccount.xdr_arms[CryptoKeyType.KEY_TYPE_MUXED_ED25519] = (
    "med25519", MuxedAccountMed25519)


class DecoratedSignature(XdrStruct):
    xdr_fields = [("hint", SignatureHint), ("signature", Signature)]


String32 = XdrString(32)
String64 = XdrString(64)
DataValue = VarOpaque(64)
UpgradeType = VarOpaque(128)
Value = VarOpaque(2**20)  # SCP opaque value


class EnvelopeType:
    ENVELOPE_TYPE_SCP = 1
    ENVELOPE_TYPE_TX = 2
    ENVELOPE_TYPE_AUTH = 3
    ENVELOPE_TYPE_SCPVALUE = 4
    ENVELOPE_TYPE_TX_FEE_BUMP = 5
    ENVELOPE_TYPE_OP_ID = 6
