"""Overlay (p2p) message vocabulary.

Role parity: reference `src/xdr/Stellar-overlay.x:179-216` (StellarMessage,
AuthenticatedMessage, Hello/Auth handshake, peers, DontHave, survey).
"""

from __future__ import annotations

from .basic import (
    Curve25519Public, Hash, HmacSha256Mac, NodeID, Signature, Uint256,
)
from .ledger import TransactionSet
from .scp import SCPEnvelope, SCPQuorumSet
from .transaction import TransactionEnvelope
from .codec import (
    EnumT, Int32, Opaque, Uint32, Uint64, VarArray, VarOpaque, XdrString,
    XdrStruct, XdrUnion,
)


class ErrorCode:
    ERR_MISC = 0
    ERR_DATA = 1
    ERR_CONF = 2
    ERR_AUTH = 3
    ERR_LOAD = 4


class Error(XdrStruct):
    xdr_fields = [("code", Int32), ("msg", XdrString(100))]


class AuthCert(XdrStruct):
    """Hourly X25519 session cert signed by the node's ed25519 identity key.
    Reference: src/overlay/PeerAuth.cpp:19-34."""
    xdr_fields = [
        ("pubkey", Curve25519Public),
        ("expiration", Uint64),
        ("sig", Signature),
    ]


class Hello(XdrStruct):
    xdr_fields = [
        ("ledgerVersion", Uint32),
        ("overlayVersion", Uint32),
        ("overlayMinVersion", Uint32),
        ("networkID", Hash),
        ("versionStr", XdrString(100)),
        ("listeningPort", Int32),
        ("peerID", NodeID),
        ("cert", AuthCert),
        ("nonce", Uint256),
    ]


class Auth(XdrStruct):
    xdr_fields = [("unused", Int32)]


class IPAddr(XdrUnion):
    IPv4 = 0
    IPv6 = 1
    xdr_arms = {0: ("ipv4", Opaque(4)), 1: ("ipv6", Opaque(16))}


class PeerAddress(XdrStruct):
    xdr_fields = [("ip", IPAddr), ("port", Uint32), ("numFailures", Uint32)]


class MessageType:
    ERROR_MSG = 0
    AUTH = 2
    DONT_HAVE = 3
    GET_PEERS = 4
    PEERS = 5
    GET_TX_SET = 6
    TX_SET = 7
    TRANSACTION = 8
    GET_SCP_QUORUMSET = 9
    SCP_QUORUMSET = 10
    SCP_MESSAGE = 11
    GET_SCP_STATE = 12
    HELLO = 13
    SURVEY_REQUEST = 14
    SURVEY_RESPONSE = 15


class DontHave(XdrStruct):
    xdr_fields = [("type", Int32), ("reqHash", Uint256)]


class SurveyMessageCommandType:
    SURVEY_TOPOLOGY = 0


class SurveyRequestMessage(XdrStruct):
    xdr_fields = [
        ("surveyorPeerID", NodeID),
        ("surveyedPeerID", NodeID),
        ("ledgerNum", Uint32),
        ("encryptionKey", Curve25519Public),
        ("commandType", Int32),
    ]


class SignedSurveyRequestMessage(XdrStruct):
    xdr_fields = [("requestSignature", Signature),
                  ("request", SurveyRequestMessage)]


EncryptedBody = VarOpaque(64000)


class SurveyResponseMessage(XdrStruct):
    xdr_fields = [
        ("surveyorPeerID", NodeID),
        ("surveyedPeerID", NodeID),
        ("ledgerNum", Uint32),
        ("commandType", Int32),
        ("encryptedBody", EncryptedBody),
    ]


class SignedSurveyResponseMessage(XdrStruct):
    xdr_fields = [("responseSignature", Signature),
                  ("response", SurveyResponseMessage)]


class PeerStats(XdrStruct):
    xdr_fields = [
        ("id", NodeID),
        ("versionStr", XdrString(100)),
        ("messagesRead", Uint64),
        ("messagesWritten", Uint64),
        ("bytesRead", Uint64),
        ("bytesWritten", Uint64),
        ("secondsConnected", Uint64),
    ]


class TopologyResponseBody(XdrStruct):
    xdr_fields = [
        ("inboundPeers", VarArray(PeerStats, 25)),
        ("outboundPeers", VarArray(PeerStats, 25)),
        ("totalInboundPeerCount", Uint32),
        ("totalOutboundPeerCount", Uint32),
    ]


class StellarMessage(XdrUnion):
    xdr_arms = {
        MessageType.ERROR_MSG: ("error", Error),
        MessageType.HELLO: ("hello", Hello),
        MessageType.AUTH: ("auth", Auth),
        MessageType.DONT_HAVE: ("dontHave", DontHave),
        MessageType.GET_PEERS: ("getPeers", None),
        MessageType.PEERS: ("peers", VarArray(PeerAddress, 100)),
        MessageType.GET_TX_SET: ("txSetHash", Uint256),
        MessageType.TX_SET: ("txSet", TransactionSet),
        MessageType.TRANSACTION: ("transaction", TransactionEnvelope),
        MessageType.GET_SCP_QUORUMSET: ("qSetHash", Uint256),
        MessageType.SCP_QUORUMSET: ("qSet", SCPQuorumSet),
        MessageType.SCP_MESSAGE: ("envelope", SCPEnvelope),
        MessageType.GET_SCP_STATE: ("getSCPLedgerSeq", Uint32),
        MessageType.SURVEY_REQUEST:
            ("signedSurveyRequestMessage", SignedSurveyRequestMessage),
        MessageType.SURVEY_RESPONSE:
            ("signedSurveyResponseMessage", SignedSurveyResponseMessage),
    }


class AuthenticatedMessageV0(XdrStruct):
    """seq + HMAC-SHA256(seq ‖ msg). Reference: src/overlay/Peer.cpp:436-439."""
    xdr_fields = [
        ("sequence", Uint64),
        ("message", StellarMessage),
        ("mac", HmacSha256Mac),
    ]


class AuthenticatedMessage(XdrUnion):
    xdr_arms = {0: ("v0", AuthenticatedMessageV0)}
