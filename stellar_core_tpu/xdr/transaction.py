"""Transaction, operation, and result types.

Role parity: reference `src/xdr/Stellar-transaction.x` (14 operation types,
envelopes incl. fee bump, signature payload, results).
"""

from __future__ import annotations

from .basic import (
    AccountID, DecoratedSignature, EnvelopeType, Hash, MuxedAccount, String32,
    String64, DataValue, Uint256,
)
from .ledger_entries import (
    Asset, OfferEntry, Price, SequenceNumber, Signer, _Ext,
)
from .codec import (
    Int32, Int64, Opaque, OptionalT, Uint32, Uint64, VarArray, VarOpaque,
    XdrString, XdrStruct, XdrUnion, XdrError, Packer,
)


class OperationType:
    CREATE_ACCOUNT = 0
    PAYMENT = 1
    PATH_PAYMENT_STRICT_RECEIVE = 2
    MANAGE_SELL_OFFER = 3
    CREATE_PASSIVE_SELL_OFFER = 4
    SET_OPTIONS = 5
    CHANGE_TRUST = 6
    ALLOW_TRUST = 7
    ACCOUNT_MERGE = 8
    INFLATION = 9
    MANAGE_DATA = 10
    BUMP_SEQUENCE = 11
    MANAGE_BUY_OFFER = 12
    PATH_PAYMENT_STRICT_SEND = 13

    ALL = list(range(14))


class CreateAccountOp(XdrStruct):
    xdr_fields = [("destination", AccountID), ("startingBalance", Int64)]


class PaymentOp(XdrStruct):
    xdr_fields = [("destination", MuxedAccount), ("asset", Asset),
                  ("amount", Int64)]


class PathPaymentStrictReceiveOp(XdrStruct):
    xdr_fields = [
        ("sendAsset", Asset), ("sendMax", Int64),
        ("destination", MuxedAccount), ("destAsset", Asset),
        ("destAmount", Int64), ("path", VarArray(Asset, 5)),
    ]


class PathPaymentStrictSendOp(XdrStruct):
    xdr_fields = [
        ("sendAsset", Asset), ("sendAmount", Int64),
        ("destination", MuxedAccount), ("destAsset", Asset),
        ("destMin", Int64), ("path", VarArray(Asset, 5)),
    ]


class ManageSellOfferOp(XdrStruct):
    xdr_fields = [("selling", Asset), ("buying", Asset), ("amount", Int64),
                  ("price", Price), ("offerID", Int64)]


class ManageBuyOfferOp(XdrStruct):
    xdr_fields = [("selling", Asset), ("buying", Asset), ("buyAmount", Int64),
                  ("price", Price), ("offerID", Int64)]


class CreatePassiveSellOfferOp(XdrStruct):
    xdr_fields = [("selling", Asset), ("buying", Asset), ("amount", Int64),
                  ("price", Price)]


class SetOptionsOp(XdrStruct):
    xdr_fields = [
        ("inflationDest", OptionalT(AccountID)),
        ("clearFlags", OptionalT(Uint32)),
        ("setFlags", OptionalT(Uint32)),
        ("masterWeight", OptionalT(Uint32)),
        ("lowThreshold", OptionalT(Uint32)),
        ("medThreshold", OptionalT(Uint32)),
        ("highThreshold", OptionalT(Uint32)),
        ("homeDomain", OptionalT(String32)),
        ("signer", OptionalT(Signer)),
    ]


class ChangeTrustOp(XdrStruct):
    xdr_fields = [("line", Asset), ("limit", Int64)]


class AllowTrustAsset(XdrUnion):
    xdr_arms = {
        1: ("assetCode4", Opaque(4)),
        2: ("assetCode12", Opaque(12)),
    }


class AllowTrustOp(XdrStruct):
    xdr_fields = [("trustor", AccountID), ("asset", AllowTrustAsset),
                  ("authorize", Uint32)]


class ManageDataOp(XdrStruct):
    xdr_fields = [("dataName", String64), ("dataValue", OptionalT(DataValue))]


class BumpSequenceOp(XdrStruct):
    xdr_fields = [("bumpTo", SequenceNumber)]


class OperationBody(XdrUnion):
    xdr_arms = {
        OperationType.CREATE_ACCOUNT: ("createAccountOp", CreateAccountOp),
        OperationType.PAYMENT: ("paymentOp", PaymentOp),
        OperationType.PATH_PAYMENT_STRICT_RECEIVE:
            ("pathPaymentStrictReceiveOp", PathPaymentStrictReceiveOp),
        OperationType.MANAGE_SELL_OFFER: ("manageSellOfferOp", ManageSellOfferOp),
        OperationType.CREATE_PASSIVE_SELL_OFFER:
            ("createPassiveSellOfferOp", CreatePassiveSellOfferOp),
        OperationType.SET_OPTIONS: ("setOptionsOp", SetOptionsOp),
        OperationType.CHANGE_TRUST: ("changeTrustOp", ChangeTrustOp),
        OperationType.ALLOW_TRUST: ("allowTrustOp", AllowTrustOp),
        OperationType.ACCOUNT_MERGE: ("destination", MuxedAccount),
        OperationType.INFLATION: ("inflation", None),
        OperationType.MANAGE_DATA: ("manageDataOp", ManageDataOp),
        OperationType.BUMP_SEQUENCE: ("bumpSequenceOp", BumpSequenceOp),
        OperationType.MANAGE_BUY_OFFER: ("manageBuyOfferOp", ManageBuyOfferOp),
        OperationType.PATH_PAYMENT_STRICT_SEND:
            ("pathPaymentStrictSendOp", PathPaymentStrictSendOp),
    }


class Operation(XdrStruct):
    xdr_fields = [("sourceAccount", OptionalT(MuxedAccount)),
                  ("body", OperationBody)]


class MemoType:
    MEMO_NONE = 0
    MEMO_TEXT = 1
    MEMO_ID = 2
    MEMO_HASH = 3
    MEMO_RETURN = 4


class Memo(XdrUnion):
    xdr_arms = {
        MemoType.MEMO_NONE: ("none", None),
        MemoType.MEMO_TEXT: ("text", XdrString(28)),
        MemoType.MEMO_ID: ("id", Uint64),
        MemoType.MEMO_HASH: ("hash", Hash),
        MemoType.MEMO_RETURN: ("retHash", Hash),
    }

    @classmethod
    def none(cls) -> "Memo":
        return cls(MemoType.MEMO_NONE)


class TimeBounds(XdrStruct):
    xdr_fields = [("minTime", Uint64), ("maxTime", Uint64)]


MAX_OPS_PER_TX = 100


class Transaction(XdrStruct):
    xdr_fields = [
        ("sourceAccount", MuxedAccount),
        ("fee", Uint32),
        ("seqNum", SequenceNumber),
        ("timeBounds", OptionalT(TimeBounds)),
        ("memo", Memo),
        ("operations", VarArray(Operation, MAX_OPS_PER_TX)),
        ("ext", _Ext),
    ]


class TransactionV1Envelope(XdrStruct):
    xdr_fields = [("tx", Transaction),
                  ("signatures", VarArray(DecoratedSignature, 20))]


class _InnerTxEnvelope(XdrUnion):
    xdr_arms = {EnvelopeType.ENVELOPE_TYPE_TX: ("v1", TransactionV1Envelope)}


class FeeBumpTransaction(XdrStruct):
    xdr_fields = [
        ("feeSource", MuxedAccount),
        ("fee", Int64),
        ("innerTx", _InnerTxEnvelope),
        ("ext", _Ext),
    ]


class FeeBumpTransactionEnvelope(XdrStruct):
    xdr_fields = [("tx", FeeBumpTransaction),
                  ("signatures", VarArray(DecoratedSignature, 20))]


class TransactionEnvelope(XdrUnion):
    xdr_arms = {
        EnvelopeType.ENVELOPE_TYPE_TX: ("v1", TransactionV1Envelope),
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
            ("feeBump", FeeBumpTransactionEnvelope),
    }

    @classmethod
    def for_tx(cls, tx: Transaction,
               signatures: list | None = None) -> "TransactionEnvelope":
        return cls(EnvelopeType.ENVELOPE_TYPE_TX,
                   TransactionV1Envelope(tx=tx, signatures=signatures or []))


class _TaggedTransaction(XdrUnion):
    xdr_arms = {
        EnvelopeType.ENVELOPE_TYPE_TX: ("tx", Transaction),
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP: ("feeBump", FeeBumpTransaction),
    }


class TransactionSignaturePayload(XdrStruct):
    """What is actually signed: SHA256(networkId || tagged tx).

    Reference: TransactionFrame::getSignaturePayload role
    (src/transactions/TransactionFrame.cpp contents-hash construction).
    """
    xdr_fields = [("networkId", Hash), ("taggedTransaction", _TaggedTransaction)]


# --- Results ---------------------------------------------------------------

class ClaimOfferAtom(XdrStruct):
    xdr_fields = [
        ("sellerID", AccountID), ("offerID", Int64),
        ("assetSold", Asset), ("amountSold", Int64),
        ("assetBought", Asset), ("amountBought", Int64),
    ]


class SimplePaymentResult(XdrStruct):
    xdr_fields = [("destination", AccountID), ("asset", Asset),
                  ("amount", Int64)]


def _code_union(name: str, success_codes_with_payload: dict,
                default_void: bool = True):
    """Build an op-result union class: success arms may carry payloads; any
    other (negative) code is void."""
    cls = type(name, (XdrUnion,), {
        "xdr_arms": dict(success_codes_with_payload),
        "xdr_default": ("code", None) if default_void else None,
    })
    return cls


class ManageOfferSuccessResultOffer(XdrUnion):
    # MANAGE_OFFER_CREATED=0 / UPDATED=1 carry the offer; DELETED=2 void
    xdr_arms = {
        0: ("created", OfferEntry),
        1: ("updated", OfferEntry),
        2: ("deleted", None),
    }


class ManageOfferSuccessResult(XdrStruct):
    xdr_fields = [("offersClaimed", VarArray(ClaimOfferAtom)),
                  ("offer", ManageOfferSuccessResultOffer)]


class PathPaymentSuccess(XdrStruct):
    xdr_fields = [("offers", VarArray(ClaimOfferAtom)),
                  ("last", SimplePaymentResult)]


class InflationPayout(XdrStruct):
    xdr_fields = [("destination", AccountID), ("amount", Int64)]


CreateAccountResult = _code_union("CreateAccountResult", {0: ("success", None)})
PaymentResult = _code_union("PaymentResult", {0: ("success", None)})
PathPaymentStrictReceiveResult = _code_union(
    "PathPaymentStrictReceiveResult", {0: ("success", PathPaymentSuccess)})
PathPaymentStrictSendResult = _code_union(
    "PathPaymentStrictSendResult", {0: ("success", PathPaymentSuccess)})
ManageSellOfferResult = _code_union(
    "ManageSellOfferResult", {0: ("success", ManageOfferSuccessResult)})
ManageBuyOfferResult = _code_union(
    "ManageBuyOfferResult", {0: ("success", ManageOfferSuccessResult)})
SetOptionsResult = _code_union("SetOptionsResult", {0: ("success", None)})
ChangeTrustResult = _code_union("ChangeTrustResult", {0: ("success", None)})
AllowTrustResult = _code_union("AllowTrustResult", {0: ("success", None)})
AccountMergeResult = _code_union(
    "AccountMergeResult", {0: ("sourceAccountBalance", Int64)})
InflationResult = _code_union(
    "InflationResult", {0: ("payouts", VarArray(InflationPayout))})
ManageDataResult = _code_union("ManageDataResult", {0: ("success", None)})
BumpSequenceResult = _code_union("BumpSequenceResult", {0: ("success", None)})


class OperationInner(XdrUnion):
    xdr_arms = {
        OperationType.CREATE_ACCOUNT: ("createAccountResult", CreateAccountResult),
        OperationType.PAYMENT: ("paymentResult", PaymentResult),
        OperationType.PATH_PAYMENT_STRICT_RECEIVE:
            ("pathPaymentStrictReceiveResult", PathPaymentStrictReceiveResult),
        OperationType.MANAGE_SELL_OFFER:
            ("manageSellOfferResult", ManageSellOfferResult),
        OperationType.CREATE_PASSIVE_SELL_OFFER:
            ("createPassiveSellOfferResult", ManageSellOfferResult),
        OperationType.SET_OPTIONS: ("setOptionsResult", SetOptionsResult),
        OperationType.CHANGE_TRUST: ("changeTrustResult", ChangeTrustResult),
        OperationType.ALLOW_TRUST: ("allowTrustResult", AllowTrustResult),
        OperationType.ACCOUNT_MERGE: ("accountMergeResult", AccountMergeResult),
        OperationType.INFLATION: ("inflationResult", InflationResult),
        OperationType.MANAGE_DATA: ("manageDataResult", ManageDataResult),
        OperationType.BUMP_SEQUENCE: ("bumpSequenceResult", BumpSequenceResult),
        OperationType.MANAGE_BUY_OFFER:
            ("manageBuyOfferResult", ManageBuyOfferResult),
        OperationType.PATH_PAYMENT_STRICT_SEND:
            ("pathPaymentStrictSendResult", PathPaymentStrictSendResult),
    }


class OperationResultCode:
    opINNER = 0
    opBAD_AUTH = -1
    opNO_ACCOUNT = -2
    opNOT_SUPPORTED = -3
    opTOO_MANY_SUBENTRIES = -4
    opEXCEEDED_WORK_LIMIT = -5


class OperationResult(XdrUnion):
    xdr_arms = {OperationResultCode.opINNER: ("tr", OperationInner)}
    xdr_default = ("code", None)

    @classmethod
    def inner(cls, op_type: int, inner_result) -> "OperationResult":
        return cls(OperationResultCode.opINNER,
                   OperationInner(op_type, inner_result))


class TransactionResultCode:
    txFEE_BUMP_INNER_SUCCESS = 1
    txSUCCESS = 0
    txFAILED = -1
    txTOO_EARLY = -2
    txTOO_LATE = -3
    txMISSING_OPERATION = -4
    txBAD_SEQ = -5
    txBAD_AUTH = -6
    txINSUFFICIENT_BALANCE = -7
    txNO_ACCOUNT = -8
    txINSUFFICIENT_FEE = -9
    txBAD_AUTH_EXTRA = -10
    txINTERNAL_ERROR = -11
    txNOT_SUPPORTED = -12
    txFEE_BUMP_INNER_FAILED = -13


class InnerTransactionResultPair(XdrStruct):
    # forward-declared; fields patched after TransactionResult defined
    xdr_fields = []


class _TxResultResult(XdrUnion):
    xdr_arms = {
        TransactionResultCode.txSUCCESS: ("results", VarArray(OperationResult)),
        TransactionResultCode.txFAILED: ("results", VarArray(OperationResult)),
        TransactionResultCode.txFEE_BUMP_INNER_SUCCESS:
            ("innerResultPair", InnerTransactionResultPair),
        TransactionResultCode.txFEE_BUMP_INNER_FAILED:
            ("innerResultPair", InnerTransactionResultPair),
    }
    xdr_default = ("code", None)


class TransactionResult(XdrStruct):
    xdr_fields = [
        ("feeCharged", Int64),
        ("result", _TxResultResult),
        ("ext", _Ext),
    ]

    @property
    def code(self) -> int:
        return self.result.disc

    @property
    def op_results(self):
        if self.result.disc in (TransactionResultCode.txSUCCESS,
                                TransactionResultCode.txFAILED):
            return self.result.value
        return []


InnerTransactionResultPair.xdr_fields = [
    ("transactionHash", Hash),
    ("result", TransactionResult),
]


class TransactionResultPair(XdrStruct):
    xdr_fields = [("transactionHash", Hash), ("result", TransactionResult)]


class TransactionResultSet(XdrStruct):
    xdr_fields = [("results", VarArray(TransactionResultPair))]
