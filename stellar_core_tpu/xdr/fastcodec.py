"""Compiled fast paths for the XDR codec.

The declarative codec in codec.py dispatches through a method call per
field per value — measured at ~60% of catchup-replay CPU time (XDR bytes
are the canonical hash form, so encode/decode sits under every hash,
every wire message, every history stream). This module compiles each type
combinator ONCE into closure-specialized functions:

    pack:   f(append, value)           append = list.append of the buffer
    unpack: f(buf, pos) -> (value, new_pos)

eliminating interpreter-level indirection (attribute lookups, Packer /
Unpacker objects, per-int bounds objects) while keeping every validation
the slow path performs: int ranges, opaque lengths, zero padding, enum
membership, max array/opaque sizes, trailing-byte checks.

Role parity: the reference gets this for free from xdrpp's generated C++
(/root/reference/src/Makefile.am:26-29); this is the Python equivalent of
that code generation, done at runtime.
"""

from __future__ import annotations

import struct
from typing import Any, Callable

from . import codec as C

_U32 = struct.Struct(">I")
_I32 = struct.Struct(">i")

PackFn = Callable[[Callable[[bytes], None], Any], None]
UnpackFn = Callable[[bytes, int], tuple]


def _pad(n: int) -> int:
    return (4 - n % 4) % 4


# --------------------------------------------------------------- compilers

def compile_pack(t: Any) -> PackFn:
    # classes must use their OWN slot (inheritance would leak a parent's
    # compiled fn onto subclasses); instances can use plain attributes
    cached = t.__dict__.get("_fast_pack") if isinstance(t, type) \
        else getattr(t, "_fast_pack", None)
    if cached is not None:
        return cached
    fn = _build_pack(t)
    try:
        t._fast_pack = fn
    except (AttributeError, TypeError):
        pass
    return fn


def compile_unpack(t: Any) -> UnpackFn:
    cached = t.__dict__.get("_fast_unpack") if isinstance(t, type) \
        else getattr(t, "_fast_unpack", None)
    if cached is not None:
        return cached
    fn = _build_unpack(t)
    try:
        t._fast_unpack = fn
    except (AttributeError, TypeError):
        pass
    return fn


def _build_pack(t: Any) -> PackFn:
    if isinstance(t, C._Int):
        s, lo, hi = t._s, t._lo, t._hi

        def f(ap, v, s=s, lo=lo, hi=hi):
            if not (lo <= v <= hi):
                raise C.XdrError("int out of range: %r" % (v,))
            ap(s.pack(v))
        return f

    if isinstance(t, C._Bool):
        def f(ap, v):
            ap(b"\x00\x00\x00\x01" if v else b"\x00\x00\x00\x00")
        return f

    if isinstance(t, C.Opaque):
        n = t.n
        pad = b"\x00" * _pad(n)

        def f(ap, v, n=n, pad=pad):
            if len(v) != n:
                raise C.XdrError("opaque[%d] got %d bytes" % (n, len(v)))
            ap(v)
            if pad:
                ap(pad)
        return f

    if isinstance(t, C.VarOpaque):
        maxn = t.maxn

        def f(ap, v, maxn=maxn):
            n = len(v)
            if n > maxn:
                raise C.XdrError("opaque<%d> got %d bytes" % (maxn, n))
            ap(_U32.pack(n))
            ap(v)
            p = _pad(n)
            if p:
                ap(b"\x00" * p)
        return f

    if isinstance(t, C.XdrString):
        inner = _build_pack(t._o)

        def f(ap, v, inner=inner):
            inner(ap, v.encode("utf-8"))
        return f

    if isinstance(t, C.FixedArray):
        elem = compile_pack(t.elem)
        n = t.n

        def f(ap, v, elem=elem, n=n):
            if len(v) != n:
                raise C.XdrError("array[%d] got %d" % (n, len(v)))
            for e in v:
                elem(ap, e)
        return f

    if isinstance(t, C.VarArray):
        elem = compile_pack(t.elem)
        maxn = t.maxn

        def f(ap, v, elem=elem, maxn=maxn):
            n = len(v)
            if n > maxn:
                raise C.XdrError("array<%d> got %d" % (maxn, n))
            ap(_U32.pack(n))
            for e in v:
                elem(ap, e)
        return f

    if isinstance(t, C.OptionalT):
        elem = compile_pack(t.elem)

        def f(ap, v, elem=elem):
            if v is None:
                ap(b"\x00\x00\x00\x00")
            else:
                ap(b"\x00\x00\x00\x01")
                elem(ap, v)
        return f

    if isinstance(t, C.EnumT):
        values = t.values

        def f(ap, v, values=values):
            if v not in values:
                raise C.XdrError("bad enum value %r" % (v,))
            ap(_I32.pack(v))
        return f

    if isinstance(t, type) and issubclass(t, C.XdrStruct):
        cell: list = []   # lazy: xdr_fields may be patched post-creation

        def f(ap, v, cls=t, cell=cell):
            if not cell:
                cell.append(tuple((n, compile_pack(ft))
                                  for n, ft in cls.xdr_fields))
            if v.__class__ is not cls and not isinstance(v, cls):
                raise C.XdrError("expected %s, got %r"
                                 % (cls.__name__, type(v)))
            for n, fp in cell[0]:
                fp(ap, getattr(v, n))
        return f

    if isinstance(t, type) and issubclass(t, C.XdrUnion):
        cell: list = []

        def f(ap, v, cls=t, cell=cell):
            if not cell:
                arms = {d: (compile_pack(at) if at is not None else None)
                        for d, (an, at) in cls.xdr_arms.items()}
                default = None
                if cls.xdr_default is not None:
                    default = compile_pack(cls.xdr_default[1]) \
                        if cls.xdr_default[1] is not None else None
                cell.append((compile_pack(cls.xdr_switch_type), arms,
                             default, cls.xdr_default is not None))
            sw, arms, default, has_default = cell[0]
            if v.__class__ is not cls and not isinstance(v, cls):
                raise C.XdrError("expected %s, got %r"
                                 % (cls.__name__, type(v)))
            disc = v.disc
            if disc in arms:
                fp = arms[disc]
            elif has_default:
                fp = default
            else:
                raise C.XdrError("%s: bad discriminant %r"
                                 % (cls.__name__, disc))
            sw(ap, disc)
            if fp is not None:
                fp(ap, v.value)
        return f

    # unknown combinator: fall back to its own pack via a Packer shim
    def f(ap, v, t=t):
        p = C.Packer()
        t.pack(p, v)
        ap(p.bytes())
    return f


def _build_unpack(t: Any) -> UnpackFn:
    if isinstance(t, C._Int):
        s = t._s
        size = s.size

        def f(buf, pos, s=s, size=size):
            try:
                v = s.unpack_from(buf, pos)[0]
            except struct.error:
                raise C.XdrError("XDR underflow at %d" % pos) from None
            return v, pos + size
        return f

    if isinstance(t, C._Bool):
        def f(buf, pos):
            w = buf[pos:pos + 4]
            if w == b"\x00\x00\x00\x00":
                return False, pos + 4
            if w == b"\x00\x00\x00\x01":
                return True, pos + 4
            if len(w) < 4:
                raise C.XdrError("XDR underflow at %d" % pos)
            raise C.XdrError("bad bool")
        return f

    if isinstance(t, C.Opaque):
        n = t.n
        padn = _pad(n)
        zero = b"\x00" * padn

        def f(buf, pos, n=n, padn=padn, zero=zero):
            end = pos + n + padn
            if end > len(buf):
                raise C.XdrError("XDR underflow at %d" % pos)
            if padn and buf[pos + n:end] != zero:
                raise C.XdrError("nonzero padding")
            return buf[pos:pos + n], end
        return f

    if isinstance(t, C.VarOpaque):
        maxn = t.maxn

        def f(buf, pos, maxn=maxn):
            try:
                n = _U32.unpack_from(buf, pos)[0]
            except struct.error:
                raise C.XdrError("XDR underflow at %d" % pos) from None
            if n > maxn:
                raise C.XdrError("opaque<%d> wire len %d" % (maxn, n))
            pos += 4
            padn = _pad(n)
            end = pos + n + padn
            if end > len(buf):
                raise C.XdrError("XDR underflow at %d" % pos)
            if padn and buf[pos + n:end] != b"\x00" * padn:
                raise C.XdrError("nonzero padding")
            return buf[pos:pos + n], end
        return f

    if isinstance(t, C.XdrString):
        inner = _build_unpack(t._o)

        def f(buf, pos, inner=inner):
            v, pos = inner(buf, pos)
            return v.decode("utf-8"), pos
        return f

    if isinstance(t, C.FixedArray):
        elem = compile_unpack(t.elem)
        n = t.n

        def f(buf, pos, elem=elem, n=n):
            out = []
            ap = out.append
            for _ in range(n):
                v, pos = elem(buf, pos)
                ap(v)
            return out, pos
        return f

    if isinstance(t, C.VarArray):
        elem = compile_unpack(t.elem)
        maxn = t.maxn

        def f(buf, pos, elem=elem, maxn=maxn):
            try:
                n = _U32.unpack_from(buf, pos)[0]
            except struct.error:
                raise C.XdrError("XDR underflow at %d" % pos) from None
            if n > maxn:
                raise C.XdrError("array<%d> wire len %d" % (maxn, n))
            pos += 4
            out = []
            ap = out.append
            for _ in range(n):
                v, pos = elem(buf, pos)
                ap(v)
            return out, pos
        return f

    if isinstance(t, C.OptionalT):
        elem = compile_unpack(t.elem)

        def f(buf, pos, elem=elem):
            w = buf[pos:pos + 4]
            if w == b"\x00\x00\x00\x00":
                return None, pos + 4
            if w == b"\x00\x00\x00\x01":
                return elem(buf, pos + 4)
            if len(w) < 4:
                raise C.XdrError("XDR underflow at %d" % pos)
            raise C.XdrError("bad optional flag")
        return f

    if isinstance(t, C.EnumT):
        values = t.values

        def f(buf, pos, values=values):
            try:
                v = _I32.unpack_from(buf, pos)[0]
            except struct.error:
                raise C.XdrError("XDR underflow at %d" % pos) from None
            if v not in values:
                raise C.XdrError("bad enum value %r" % (v,))
            return v, pos + 4
        return f

    if isinstance(t, type) and issubclass(t, C.XdrStruct):
        cell: list = []

        def f(buf, pos, cls=t, cell=cell):
            if not cell:
                cell.append(tuple((n, compile_unpack(ft))
                                  for n, ft in cls.xdr_fields))
            obj = cls.__new__(cls)
            d = obj.__dict__
            for n, fu in cell[0]:
                d[n], pos = fu(buf, pos)
            return obj, pos
        return f

    if isinstance(t, type) and issubclass(t, C.XdrUnion):
        cell: list = []

        def f(buf, pos, cls=t, cell=cell):
            if not cell:
                arms = {d: (compile_unpack(at) if at is not None else None)
                        for d, (an, at) in cls.xdr_arms.items()}
                default = None
                if cls.xdr_default is not None:
                    default = compile_unpack(cls.xdr_default[1]) \
                        if cls.xdr_default[1] is not None else None
                cell.append((compile_unpack(cls.xdr_switch_type), arms,
                             default, cls.xdr_default is not None))
            sw, arms, default, has_default = cell[0]
            disc, pos = sw(buf, pos)
            if disc in arms:
                fu = arms[disc]
            elif has_default:
                fu = default
            else:
                raise C.XdrError("%s: bad discriminant %r"
                                 % (cls.__name__, disc))
            obj = cls.__new__(cls)
            obj.disc = disc
            if fu is not None:
                obj.value, pos = fu(buf, pos)
            else:
                obj.value = None
            return obj, pos
        return f

    # unknown combinator: fall back to its own unpack via an Unpacker shim
    def f(buf, pos, t=t):
        u = C.Unpacker(buf)
        u._pos = pos
        v = t.unpack(u)
        return v, u._pos
    return f


# ------------------------------------------------------------ deep copy

def compile_copy(t: Any) -> Callable[[Any], Any]:
    """Compiled structural deep copy — the LedgerTxn copy-on-write
    primitive. ~4x cheaper than the pack+unpack round-trip it replaces
    (no byte encoding, no validation re-runs; immutable leaves — ints,
    bytes, strings, enums — pass through by reference)."""
    cached = t.__dict__.get("_fast_copy") if isinstance(t, type) \
        else getattr(t, "_fast_copy", None)
    if cached is not None:
        return cached
    fn = _build_copy(t) or (lambda v: v)
    try:
        t._fast_copy = fn
    except (AttributeError, TypeError):
        pass
    return fn


def _copy_of(t: Any):
    """Like _build_copy, but recurses through the caching compile_copy for
    class types so shared nested structs/unions compile once (matches how
    _build_pack recurses via compile_pack)."""
    if isinstance(t, type) and issubclass(t, (C.XdrStruct, C.XdrUnion)):
        return compile_copy(t)
    return _build_copy(t)


def _build_copy(t: Any):
    """Returns a copy fn, or None meaning 'values of this type are
    immutable — identity suffices' (lets containers of leaves shortcut
    to a plain list() copy)."""
    if isinstance(t, (C._Int, C._Bool, C.Opaque, C.VarOpaque,
                      C.XdrString, C.EnumT)):
        return None

    if isinstance(t, (C.FixedArray, C.VarArray)):
        elem = _copy_of(t.elem)
        if elem is None:
            return lambda v: list(v)
        return lambda v, elem=elem: [elem(e) for e in v]

    if isinstance(t, C.OptionalT):
        elem = _copy_of(t.elem)
        if elem is None:
            return None
        return lambda v, elem=elem: None if v is None else elem(v)

    if isinstance(t, type) and issubclass(t, C.XdrStruct):
        cell: list = []   # lazy: xdr_fields may be patched post-creation

        def f(v, cls=t, cell=cell):
            if not cell:
                cell.append(tuple((n, _copy_of(ft))
                                  for n, ft in cls.xdr_fields))
            obj = cls.__new__(cls)
            d = obj.__dict__
            s = v.__dict__
            for n, fc in cell[0]:
                x = s[n]
                d[n] = x if fc is None else fc(x)
            return obj
        return f

    if isinstance(t, type) and issubclass(t, C.XdrUnion):
        cell: list = []

        def f(v, cls=t, cell=cell):
            if not cell:
                arms = {d: _copy_of(at) if at is not None else None
                        for d, (an, at) in cls.xdr_arms.items()}
                default = None
                if cls.xdr_default is not None and \
                        cls.xdr_default[1] is not None:
                    default = _copy_of(cls.xdr_default[1])
                cell.append((arms, default))
            arms, default = cell[0]
            obj = cls.__new__(cls)
            obj.disc = v.disc
            # unknown disc can't occur on a validly-built value; void
            # arms carry value None, where identity is right anyway
            fc = arms.get(v.disc, default)
            obj.value = v.value if fc is None else fc(v.value)
            return obj
        return f

    # unknown combinator: round-trip through bytes (always correct)
    def f(v, t=t):
        out: list = []
        compile_pack(t)(out.append, v)
        got, _pos = compile_unpack(t)(b"".join(out), 0)
        return got
    return f
