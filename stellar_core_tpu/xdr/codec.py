"""XDR (RFC 4506) codec — the canonical wire and hash format.

Role parity: reference `src/xdr/*.x` compiled by xdrc via xdrpp
(/root/reference/src/Makefile.am:26-29); XDR bytes are the canonical hashed
form (/root/reference/docs/architecture.md:50-52). This is a from-scratch
declarative codec: types are built from combinators and struct/union classes
declare `xdr_fields` / `xdr_union` specs. Big-endian, 4-byte alignment.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Optional as TOptional


class XdrError(Exception):
    pass


class Packer:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def put(self, b: bytes) -> None:
        self._parts.append(b)

    def bytes(self) -> bytes:
        return b"".join(self._parts)


class Unpacker:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def take(self, n: int) -> bytes:
        if self._pos + n > len(self._buf):
            raise XdrError("XDR underflow: need %d bytes at %d, have %d"
                           % (n, self._pos, len(self._buf)))
        b = self._buf[self._pos:self._pos + n]
        self._pos += n
        return b

    def done(self) -> bool:
        return self._pos == len(self._buf)

    def assert_done(self) -> None:
        if not self.done():
            raise XdrError("XDR trailing bytes: %d left" % (len(self._buf) - self._pos))


def _pad(n: int) -> int:
    return (4 - n % 4) % 4


# ---------------------------------------------------------------------------
# Type combinators. Each type object has pack(p, v) and unpack(u) -> v.
# ---------------------------------------------------------------------------

class _Int:
    def __init__(self, fmt: str, lo: int, hi: int) -> None:
        self._s = struct.Struct(fmt)
        self._lo, self._hi = lo, hi

    def pack(self, p: Packer, v: int) -> None:
        if not (self._lo <= v <= self._hi):
            raise XdrError("int out of range: %r" % (v,))
        p.put(self._s.pack(v))

    def unpack(self, u: Unpacker) -> int:
        return self._s.unpack(u.take(self._s.size))[0]


Int32 = _Int(">i", -(2**31), 2**31 - 1)
Uint32 = _Int(">I", 0, 2**32 - 1)
Int64 = _Int(">q", -(2**63), 2**63 - 1)
Uint64 = _Int(">Q", 0, 2**64 - 1)


class _Bool:
    def pack(self, p: Packer, v: bool) -> None:
        Uint32.pack(p, 1 if v else 0)

    def unpack(self, u: Unpacker) -> bool:
        x = Uint32.unpack(u)
        if x not in (0, 1):
            raise XdrError("bad bool %d" % x)
        return bool(x)


Bool = _Bool()


class Opaque:
    """Fixed-length opaque."""

    def __init__(self, n: int) -> None:
        self.n = n

    def pack(self, p: Packer, v: bytes) -> None:
        if len(v) != self.n:
            raise XdrError("opaque[%d] got %d bytes" % (self.n, len(v)))
        p.put(v)
        p.put(b"\x00" * _pad(self.n))

    def unpack(self, u: Unpacker) -> bytes:
        v = u.take(self.n)
        pad = u.take(_pad(self.n))
        if pad != b"\x00" * len(pad):
            raise XdrError("nonzero padding")
        return v


class VarOpaque:
    """Variable-length opaque with max size."""

    def __init__(self, maxn: int = 2**32 - 1) -> None:
        self.maxn = maxn

    def pack(self, p: Packer, v: bytes) -> None:
        if len(v) > self.maxn:
            raise XdrError("opaque<%d> got %d bytes" % (self.maxn, len(v)))
        Uint32.pack(p, len(v))
        p.put(v)
        p.put(b"\x00" * _pad(len(v)))

    def unpack(self, u: Unpacker) -> bytes:
        n = Uint32.unpack(u)
        if n > self.maxn:
            raise XdrError("opaque<%d> wire len %d" % (self.maxn, n))
        v = u.take(n)
        pad = u.take(_pad(n))
        if pad != b"\x00" * len(pad):
            raise XdrError("nonzero padding")
        return v


class XdrString:
    def __init__(self, maxn: int = 2**32 - 1) -> None:
        self._o = VarOpaque(maxn)

    def pack(self, p: Packer, v: str) -> None:
        self._o.pack(p, v.encode("utf-8"))

    def unpack(self, u: Unpacker) -> str:
        return self._o.unpack(u).decode("utf-8")


class FixedArray:
    def __init__(self, elem: Any, n: int) -> None:
        self.elem, self.n = elem, n

    def pack(self, p: Packer, v: list) -> None:
        if len(v) != self.n:
            raise XdrError("array[%d] got %d" % (self.n, len(v)))
        for e in v:
            self.elem.pack(p, e)

    def unpack(self, u: Unpacker) -> list:
        return [self.elem.unpack(u) for _ in range(self.n)]


class VarArray:
    def __init__(self, elem: Any, maxn: int = 2**32 - 1) -> None:
        self.elem, self.maxn = elem, maxn

    def pack(self, p: Packer, v: list) -> None:
        if len(v) > self.maxn:
            raise XdrError("array<%d> got %d" % (self.maxn, len(v)))
        Uint32.pack(p, len(v))
        for e in v:
            self.elem.pack(p, e)

    def unpack(self, u: Unpacker) -> list:
        n = Uint32.unpack(u)
        if n > self.maxn:
            raise XdrError("array<%d> wire len %d" % (self.maxn, n))
        return [self.elem.unpack(u) for _ in range(n)]


class OptionalT:
    """XDR optional (pointer): bool then value."""

    def __init__(self, elem: Any) -> None:
        self.elem = elem

    def pack(self, p: Packer, v: Any) -> None:
        if v is None:
            Uint32.pack(p, 0)
        else:
            Uint32.pack(p, 1)
            self.elem.pack(p, v)

    def unpack(self, u: Unpacker) -> Any:
        if Uint32.unpack(u) == 0:
            return None
        return self.elem.unpack(u)


class EnumT:
    """Enum restricted to a known value set (pack rejects unknowns)."""

    def __init__(self, values: dict[int, str]) -> None:
        self.values = values

    def pack(self, p: Packer, v: int) -> None:
        if v not in self.values:
            raise XdrError("bad enum value %r" % (v,))
        Int32.pack(p, v)

    def unpack(self, u: Unpacker) -> int:
        v = Int32.unpack(u)
        if v not in self.values:
            raise XdrError("bad enum value %r" % (v,))
        return v


class XdrStruct:
    """Base for declarative structs: subclasses set xdr_fields = [(name, type)]."""

    xdr_fields: list[tuple[str, Any]] = []

    def __init__(self, **kw: Any) -> None:
        names = [n for n, _ in self.xdr_fields]
        for n in names:
            if n not in kw:
                raise TypeError("%s missing field %s" % (type(self).__name__, n))
            v = kw.pop(n)
            if type(v) is tuple:  # normalize so field-wise __eq__ is exact
                v = list(v)
            setattr(self, n, v)
        if kw:
            raise TypeError("%s unknown fields %s" % (type(self).__name__, list(kw)))

    @classmethod
    def pack(cls, p: Packer, v: "XdrStruct") -> None:
        if not isinstance(v, cls):
            raise XdrError("expected %s, got %r" % (cls.__name__, type(v)))
        for n, t in cls.xdr_fields:
            t.pack(p, getattr(v, n))

    @classmethod
    def unpack(cls, u: Unpacker) -> "XdrStruct":
        vals = {n: t.unpack(u) for n, t in cls.xdr_fields}
        return cls(**vals)

    # value semantics -------------------------------------------------------
    def to_xdr(self) -> bytes:
        return xdr_bytes(type(self), self)

    @classmethod
    def from_xdr(cls, b: bytes) -> "XdrStruct":
        return xdr_from(cls, b)

    def __eq__(self, other: Any) -> bool:
        # field-wise (values are ints/bytes/lists/nested XDR, where ==
        # recurses) — equivalent to comparing canonical bytes, without
        # serializing both sides
        if type(self) is not type(other):
            return False
        for n, _t in self.xdr_fields:
            if getattr(self, n) != getattr(other, n):
                return False
        return True

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.to_xdr()))

    def __repr__(self) -> str:
        fs = ", ".join("%s=%r" % (n, getattr(self, n)) for n, _ in self.xdr_fields)
        return "%s(%s)" % (type(self).__name__, fs)


class XdrUnion:
    """Discriminated union: subclasses set xdr_switch_type (an int/enum type)
    and xdr_arms = {disc_value: (arm_name, arm_type_or_None)}.
    xdr_default = (arm_name, type) optionally handles unknown discriminants.
    """

    xdr_switch_type: Any = Int32
    xdr_arms: dict[int, tuple[str, Any]] = {}
    xdr_default: TOptional[tuple[str, Any]] = None

    def __init__(self, disc: int, value: Any = None) -> None:
        self.disc = disc
        if type(value) is tuple:  # normalize so field-wise __eq__ is exact
            value = list(value)
        self.value = value

    @classmethod
    def _arm(cls, disc: int) -> tuple[str, Any]:
        if disc in cls.xdr_arms:
            return cls.xdr_arms[disc]
        if cls.xdr_default is not None:
            return cls.xdr_default
        raise XdrError("%s: bad discriminant %r" % (cls.__name__, disc))

    @classmethod
    def pack(cls, p: Packer, v: "XdrUnion") -> None:
        if not isinstance(v, cls):
            raise XdrError("expected %s, got %r" % (cls.__name__, type(v)))
        name, t = cls._arm(v.disc)
        cls.xdr_switch_type.pack(p, v.disc)
        if t is not None:
            t.pack(p, v.value)

    @classmethod
    def unpack(cls, u: Unpacker) -> "XdrUnion":
        disc = cls.xdr_switch_type.unpack(u)
        name, t = cls._arm(disc)
        value = t.unpack(u) if t is not None else None
        return cls(disc, value)

    def to_xdr(self) -> bytes:
        return xdr_bytes(type(self), self)

    @classmethod
    def from_xdr(cls, b: bytes) -> "XdrUnion":
        return xdr_from(cls, b)

    def __eq__(self, other: Any) -> bool:
        # structural, like XdrStruct.__eq__
        return (type(self) is type(other) and self.disc == other.disc
                and self.value == other.value)

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.to_xdr()))

    def __repr__(self) -> str:
        name, _ = type(self)._arm(self.disc)
        return "%s(%s=%r)" % (type(self).__name__, name, self.value)


_fastcodec = None  # lazy module ref (fastcodec imports this module)
_native_xdr = None  # lazy: the stellar_core_tpu.native MODULE, or False


def _native_of(t: Any, attr: str):
    """Per-type native (de)serializer (C extension), cached on the class;
    False marks types the engine can't express."""
    global _native_xdr
    if _native_xdr is None:
        try:
            from .. import native as _native_xdr
        except Exception:
            _native_xdr = False
    if _native_xdr is False:
        return None
    slot = "_native_" + attr
    cached = t.__dict__.get(slot) if isinstance(t, type) \
        else getattr(t, slot, None)
    if cached is None:
        maker = getattr(_native_xdr, "xdr_%s_fn" % attr)
        cached = maker(t) or False
        try:
            setattr(t, slot, cached)
        except (AttributeError, TypeError):
            return cached or None
    return cached or None


def _native_pack_of(t: Any):
    return _native_of(t, "pack")


def xdr_bytes(t: Any, v: Any) -> bytes:
    nf = _native_pack_of(t)
    if nf is not None:
        return nf(v)
    global _fastcodec
    if _fastcodec is None:
        from . import fastcodec as _fc
        _fastcodec = _fc
    out: list[bytes] = []
    _fastcodec.compile_pack(t)(out.append, v)
    return b"".join(out)


def xdr_from(t: Any, b: bytes) -> Any:
    nf = _native_of(t, "unpack")
    if nf is not None:
        v, pos = nf(b)
    else:
        global _fastcodec
        if _fastcodec is None:
            from . import fastcodec as _fc
            _fastcodec = _fc
        v, pos = _fastcodec.compile_unpack(t)(b, 0)
    if pos != len(b):
        raise XdrError("XDR trailing bytes: %d left" % (len(b) - pos))
    return v
