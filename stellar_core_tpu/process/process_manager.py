"""ProcessManager: async subprocess execution ("async system()").

Role parity: reference `src/process/ProcessManager{.h,Impl.cpp}:33-553` —
bounded-concurrency subprocess runner; completion events delivered on the
main loop. Python subprocess.Popen + a reaper thread replaces the
fork/exec + SIGCHLD machinery.
"""

from __future__ import annotations

import shlex
import subprocess
import threading
from collections import deque
from typing import Callable, Deque, List, Optional

from ..util.log import get_logger
from ..util.timer import VirtualClock

log = get_logger("Process")


class ProcessExitEvent:
    """Completion handle: register a callback receiving the exit code."""

    def __init__(self, cmd: str) -> None:
        self.cmd = cmd
        self.exit_code: Optional[int] = None
        self._cbs: List[Callable[[int], None]] = []
        self._popen: Optional[subprocess.Popen] = None
        self.cancelled = False

    def add_done_callback(self, cb: Callable[[int], None]) -> None:
        if self.exit_code is not None:
            cb(self.exit_code)
        else:
            self._cbs.append(cb)

    def _complete(self, code: int) -> None:
        self.exit_code = code
        cbs, self._cbs = self._cbs, []
        for cb in cbs:
            cb(code)


class ProcessManager:
    def __init__(self, clock: VirtualClock,
                 max_concurrent: int = 16) -> None:
        self.clock = clock
        self.max_concurrent = max_concurrent
        self._queue: Deque[ProcessExitEvent] = deque()
        self._running: List[ProcessExitEvent] = []
        self._lock = threading.Lock()
        self._shutdown = False

    def run_process(self, cmd: str,
                    out_file: Optional[str] = None) -> ProcessExitEvent:
        ev = ProcessExitEvent(cmd)
        ev._out_file = out_file
        with self._lock:
            self._queue.append(ev)
        self._maybe_start()
        return ev

    def num_running(self) -> int:
        with self._lock:
            return len(self._running)

    def _maybe_start(self) -> None:
        with self._lock:
            while (len(self._running) < self.max_concurrent and
                   self._queue and not self._shutdown):
                ev = self._queue.popleft()
                if ev.cancelled:
                    continue
                try:
                    stdout = (open(ev._out_file, "wb")
                              if ev._out_file else subprocess.DEVNULL)
                    ev._popen = subprocess.Popen(
                        shlex.split(ev.cmd), stdout=stdout,
                        stderr=subprocess.DEVNULL)
                except Exception as e:
                    log.warning("spawn failed: %s (%s)", ev.cmd, e)
                    self.clock.post_to_main(lambda e=ev: e._complete(127))
                    continue
                self._running.append(ev)
                t = threading.Thread(target=self._reap, args=(ev,),
                                     daemon=True)
                t.start()

    def _reap(self, ev: ProcessExitEvent) -> None:
        code = ev._popen.wait()
        if getattr(ev, "_out_file", None) and ev._popen.stdout:
            try:
                ev._popen.stdout.close()
            except Exception:
                pass
        with self._lock:
            if ev in self._running:
                self._running.remove(ev)
        self.clock.post_to_main(lambda: ev._complete(code))
        self._maybe_start()

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._queue.clear()
            for ev in self._running:
                try:
                    ev._popen.terminate()
                except Exception:
                    pass
