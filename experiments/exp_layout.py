"""Layout experiment: fe_mul throughput, batch-first (B,20) vs batch-last (20,B).

Hypothesis: minor dims of 20/39 pad to 128 lanes on TPU -> ~15-30% VPU
utilization; putting the batch on the minor (lane) dim should win big.
"""
import time
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

import sys
sys.path.insert(0, "/root/repo")
from stellar_core_tpu.ops import field as F

B = 8192
NITER = 200

# ---------------- batch-first (current) ----------------

@jax.jit
def chain_first(x, y):
    def body(i, x):
        return F.fe_mul(x, y)
    return jax.lax.fori_loop(0, NITER, body, x)

# ---------------- batch-last ----------------

NLIMBS, LB, MASK, FOLD = F.NLIMBS, F.LIMB_BITS, F.LIMB_MASK, F.FOLD

def carry_round_T(c):
    lo = c & MASK
    hi = c >> LB
    wrapped = jnp.concatenate([hi[19:20] * FOLD, hi[:19]], axis=0)
    return lo + wrapped

def fe_mul_T(a, b):
    # columns: c[k] = sum_{i+j=k} a_i b_j  -> (39, B)
    parts = []
    zb = jnp.zeros((1, a.shape[-1]), jnp.int32)
    acc = jnp.zeros((39, a.shape[-1]), jnp.int32)
    # accumulate via padded adds; static slices
    terms = []
    for i in range(NLIMBS):
        p = a[i][None, :] * b          # (20, B)
        pad_lo = jnp.zeros((i, a.shape[-1]), jnp.int32)
        pad_hi = jnp.zeros((19 - i, a.shape[-1]), jnp.int32)
        terms.append(jnp.concatenate([pad_lo, p, pad_hi], axis=0))
    c = sum(terms)
    # widening carry round
    lo = c & MASK
    hi = c >> LB
    z1 = jnp.zeros((1, a.shape[-1]), jnp.int32)
    c = jnp.concatenate([lo, z1], axis=0) + jnp.concatenate([z1, hi], axis=0)
    low = c[:NLIMBS] + FOLD * c[NLIMBS:]
    for _ in range(2):
        low = carry_round_T(low)
    return low

@jax.jit
def chain_last(x, y):
    def body(i, x):
        return fe_mul_T(x, y)
    return jax.lax.fori_loop(0, NITER, body, x)


def bench(fn, *args, tag=""):
    t0 = time.perf_counter()
    r = fn(*args)
    r.block_until_ready()
    tc = time.perf_counter() - t0
    best = 1e9
    for _ in range(5):
        t0 = time.perf_counter()
        fn(*args).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    per_mul_ns = best / NITER / B * 1e9
    print(f"{tag}: compile {tc:.1f}s, best {best*1e3:.2f}ms for {NITER} muls "
          f"x {B} batch -> {per_mul_ns:.2f} ns/fe_mul/item", flush=True)
    return best


def main():
    rng = np.random.default_rng(0)
    xf_np = rng.integers(0, 8191, (B, NLIMBS), dtype=np.int32)
    yf_np = rng.integers(0, 8191, (B, NLIMBS), dtype=np.int32)
    xf = jnp.asarray(xf_np)
    yf = jnp.asarray(yf_np)
    xl = jnp.asarray(np.ascontiguousarray(xf_np.T))
    yl = jnp.asarray(np.ascontiguousarray(yf_np.T))

    # correctness cross-check (jitted: eager dispatch through the axon relay
    # pays per-op RTT and takes forever)
    chk_a = jax.jit(lambda x, y: F.fe_freeze(F.fe_mul(x, y)))
    chk_b = jax.jit(lambda x, y: F.fe_freeze(fe_mul_T(x, y).T))
    a = np.asarray(chk_a(xf, yf))
    b = np.asarray(chk_b(xl, yl))
    assert np.array_equal(a, b), "mismatch!"
    print("correctness ok", flush=True)

    t_first = bench(chain_first, xf, yf, tag="batch-first (B,20)")
    t_last = bench(chain_last, xl, yl, tag="batch-last (20,B)")
    print(f"speedup: {t_first / t_last:.2f}x")


if __name__ == "__main__":
    main()
