# Makes `import tools.bench_compare` work from the repo root (bench.py,
# tests); the scripts themselves also run directly.
