#!/usr/bin/env bash
# Build all three native C extensions (prep / ed25519c / applyc, plus
# the xdrc serializer) with AddressSanitizer + UndefinedBehaviorSanitizer
# into stellar_core_tpu/native/build/sanitized/, and print the LD_PRELOAD
# line needed to run Python against them.
#
#   tools/build_native_sanitized.sh          # build
#   tools/build_native_sanitized.sh --check  # build + run the native
#                                            # differential oracles under ASan
#
# The pytest equivalent of --check is the `sanitize` marker:
#   python -m pytest tests/test_native_sanitized.py -m sanitize
set -euo pipefail
cd "$(dirname "$0")/.."

LIBASAN="$(cc -print-file-name=libasan.so)"
if [ ! -e "$LIBASAN" ]; then
    echo "error: cc has no libasan.so — install gcc's sanitizer runtime" >&2
    exit 2
fi
# libstdc++ must be resolvable when ASan's interceptors initialize, or
# the first C++ throw (JAX/XLA) dies with "real___cxa_throw != 0"
PRELOAD="$LIBASAN $(cc -print-file-name=libstdc++.so)"

# build phase needs no preload (the compiler links the runtime); loading
# the resulting .so does, so the import probes run under LD_PRELOAD.
# detect_leaks=0: CPython intentionally leaks at exit and would drown
# real reports.
SCT_SANITIZE=1 LD_PRELOAD="$PRELOAD" ASAN_OPTIONS=detect_leaks=0 \
python - <<'EOF'
from stellar_core_tpu import native

built = {
    "prep (libsctprep)": native.available(),
    "ed25519c (libscted25519)": native.ed25519_native() is not None,
    "applyc (_sctapply)": native.apply_engine() is not None,
    "xdrc (_sctxdr)": (native._compile_xdr_ext() or True) and
                      native._XDR_MOD is not None,
}
for name, ok in built.items():
    print("%-28s %s" % (name, "OK" if ok else "FAILED"))
if not all(built.values()):
    raise SystemExit(1)
print("sanitized build dir:", native._BUILD)
EOF

echo
echo "run the differential oracles under ASan/UBSan with:"
echo "  SCT_SANITIZE=1 LD_PRELOAD=\"$PRELOAD\" ASAN_OPTIONS=detect_leaks=0 \\"
echo "    python -m pytest tests/test_native_prep.py tests/test_native_apply.py tests/test_native_xdr.py -q"

if [ "${1:-}" = "--check" ]; then
    SCT_SANITIZE=1 LD_PRELOAD="$PRELOAD" ASAN_OPTIONS=detect_leaks=0 \
    python -m pytest tests/test_native_prep.py tests/test_native_apply.py \
        tests/test_native_xdr.py -q -p no:cacheprovider
fi
