#!/usr/bin/env bash
# Build the native C extensions (prep / ed25519c / applyc, plus the xdrc
# serializer) with sanitizers and print the LD_PRELOAD line needed to
# run Python against them.
#
#   tools/build_native_sanitized.sh          # ASan/UBSan build -> build/sanitized/
#   tools/build_native_sanitized.sh --tsan   # ThreadSanitizer build -> build/tsan/
#   tools/build_native_sanitized.sh --check  # build BOTH + run the native
#                                            # differential oracles under
#                                            # ASan/UBSan AND the
#                                            # ParallelDiffHarness legs
#                                            # under TSan
#
# The pytest equivalent of --check is the `sanitize` marker:
#   python -m pytest tests/test_native_sanitized.py -m sanitize
#
# ASan and TSan runtimes cannot coexist in one process: each leg is its
# own build dir (SCT_SANITIZE=1 vs SCT_SANITIZE=thread) and its own
# python invocation.
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-}"

LIBSTDCPP="$(cc -print-file-name=libstdc++.so)"

build_asan() {
    local LIBASAN
    LIBASAN="$(cc -print-file-name=libasan.so)"
    if [ ! -e "$LIBASAN" ]; then
        echo "error: cc has no libasan.so — install gcc's sanitizer runtime" >&2
        exit 2
    fi
    # libstdc++ must be resolvable when ASan's interceptors initialize, or
    # the first C++ throw (JAX/XLA) dies with "real___cxa_throw != 0"
    ASAN_PRELOAD="$LIBASAN $LIBSTDCPP"
    # build phase needs no preload (the compiler links the runtime); loading
    # the resulting .so does, so the import probes run under LD_PRELOAD.
    # detect_leaks=0: CPython intentionally leaks at exit and would drown
    # real reports.
    SCT_SANITIZE=1 LD_PRELOAD="$ASAN_PRELOAD" ASAN_OPTIONS=detect_leaks=0 \
    python - <<'EOF'
from stellar_core_tpu import native

built = {
    "prep (libsctprep)": native.available(),
    "ed25519c (libscted25519)": native.ed25519_native() is not None,
    "applyc (_sctapply)": native.apply_engine() is not None,
    "xdrc (_sctxdr)": (native._compile_xdr_ext() or True) and
                      native._XDR_MOD is not None,
}
for name, ok in built.items():
    print("%-28s %s" % (name, "OK" if ok else "FAILED"))
if not all(built.values()):
    raise SystemExit(1)
print("ASan/UBSan build dir:", native._BUILD)
EOF

    echo
    echo "run the differential oracles under ASan/UBSan with:"
    echo "  SCT_SANITIZE=1 LD_PRELOAD=\"$ASAN_PRELOAD\" ASAN_OPTIONS=detect_leaks=0 \\"
    echo "    python -m pytest tests/test_native_prep.py tests/test_native_apply.py tests/test_native_xdr.py -q"
}

build_tsan() {
    local LIBTSAN
    LIBTSAN="$(cc -print-file-name=libtsan.so)"
    if [ ! -e "$LIBTSAN" ]; then
        echo "error: cc has no libtsan.so — install gcc's sanitizer runtime" >&2
        exit 2
    fi
    TSAN_PRELOAD="$LIBTSAN $LIBSTDCPP"
    # TSan build runs WITHOUT the preload: a TSan-preloaded python
    # forking gcc can deadlock in the runtime's fork interceptor. The
    # .so files land in build/tsan/ (loading them here fails by design);
    # the run phase preloads libtsan against the cached artifacts.
    SCT_SANITIZE=thread python - <<'EOF'
import glob
import os

from stellar_core_tpu import native

assert native.SANITIZE_MODE == "thread" and native._BUILD.endswith("tsan")
native.available()
native.ed25519_native()
native.apply_engine()
native._compile_xdr_ext()
for pat in ("libsctprep-*.so", "libscted25519-*.so",
            "_sctapply-*.so", "_sctxdr-*.so"):
    hits = glob.glob(os.path.join(native._BUILD, pat))
    print("%-24s %s" % (pat, "OK" if hits else "FAILED"))
    if not hits:
        raise SystemExit(1)
print("TSan build dir:", native._BUILD)
EOF

    echo
    echo "race-check the GIL-released cluster pool under TSan with:"
    echo "  SCT_SANITIZE=thread LD_PRELOAD=\"$TSAN_PRELOAD\" TSAN_OPTIONS=halt_on_error=0 \\"
    echo "    python -m pytest 'tests/test_native_apply.py::test_native_apply_parallel_equality' \\"
    echo "      'tests/test_native_apply.py::test_native_apply_parallel_seeded' -q"
}

case "$MODE" in
--tsan)
    build_tsan
    ;;
--check)
    build_asan
    echo
    build_tsan
    echo
    echo "== ASan/UBSan leg: native differential oracles =="
    SCT_SANITIZE=1 LD_PRELOAD="$ASAN_PRELOAD" ASAN_OPTIONS=detect_leaks=0 \
    python -m pytest tests/test_native_prep.py tests/test_native_apply.py \
        tests/test_native_xdr.py -q -p no:cacheprovider
    echo
    echo "== TSan leg: ParallelDiffHarness (forced-parallel, seeded) =="
    SCT_SANITIZE=thread LD_PRELOAD="$TSAN_PRELOAD" \
        TSAN_OPTIONS=halt_on_error=0 \
    python -m pytest \
        'tests/test_native_apply.py::test_native_apply_parallel_equality' \
        'tests/test_native_apply.py::test_native_apply_parallel_seeded' \
        -q -p no:cacheprovider
    ;;
"")
    build_asan
    ;;
*)
    echo "usage: tools/build_native_sanitized.sh [--tsan|--check]" >&2
    exit 2
    ;;
esac
