#!/usr/bin/env python3
"""Perf-regression ledger (ISSUE 6): normalize bench artifacts into
`bench/history.jsonl`, validate their schemas, and gate runs against
the best committed record per (metric, platform).

The six BENCH_r*.json and five MULTICHIP_r*.json snapshots each use one
of three shapes (raw bench output, driver wrapper with a `parsed` blob,
multichip driver record); this module flattens all of them into one
normalized record per measurement:

    {"metric": "replay_ledgers_per_sec", "unit": "ledgers/s",
     "value": 3.34, "platform": "tpu", "direction": "higher",
     "source": "BENCH_r05.json", "round": 5,
     "at_unix": 1785466800, "commit": null}

`direction` says which way is better — the comparator is direction-
aware, so a latency metric regresses UP while a throughput metric
regresses DOWN. `platform` keys baselines apart: a tiny CPU compare leg
("cpu-tiny") never gates against full-leg or device history.

CLI (also driven by `bench.py --compare [--record]`):

    tools/bench_compare.py ingest [--out bench/history.jsonl] [files...]
    tools/bench_compare.py check  [files...]      (alias: --check)
    tools/bench_compare.py compare --current FILE
        [--history bench/history.jsonl] [--tolerance 0.1]

`check` exits 1 on any malformed committed artifact — a bench snapshot
that silently drops out of the trajectory is itself a regression.
`compare` exits 1 on any regression beyond tolerance.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_HISTORY = os.path.join("bench", "history.jsonl")

DIRECTIONS = ("higher", "lower")
REQUIRED_FIELDS = ("metric", "unit", "value", "platform", "direction",
                   "source")

# device platforms whose compile/latency numbers are meaningful
_DEVICE_PLATFORMS = ("tpu", "axon")


# --------------------------------------------------------------------------
# record construction + validation

def make_record(metric: str, unit: str, value, platform: str,
                direction: str, source: str,
                round_no: Optional[int] = None,
                at_unix: Optional[int] = None,
                commit: Optional[str] = None) -> dict:
    return {"metric": metric, "unit": unit, "value": value,
            "platform": platform, "direction": direction,
            "source": source, "round": round_no,
            "at_unix": at_unix, "commit": commit}


def validate_record(rec, where: str = "") -> List[str]:
    errs: List[str] = []
    if not isinstance(rec, dict):
        return ["%s: record is not an object: %r" % (where, rec)]
    for k in REQUIRED_FIELDS:
        if k not in rec:
            errs.append("%s: missing field %r" % (where, k))
    for k in ("metric", "unit", "platform", "source"):
        if k in rec and not isinstance(rec[k], str):
            errs.append("%s: field %r must be a string, got %r"
                        % (where, k, rec[k]))
    v = rec.get("value")
    if "value" in rec and (isinstance(v, bool) or
                           not isinstance(v, (int, float)) or
                           not math.isfinite(v)):
        errs.append("%s: field 'value' must be a finite number, got %r"
                    % (where, v))
    if "direction" in rec and rec["direction"] not in DIRECTIONS:
        errs.append("%s: field 'direction' must be one of %s, got %r"
                    % (where, "/".join(DIRECTIONS), rec.get("direction")))
    for k in ("round", "at_unix"):
        if rec.get(k) is not None and not isinstance(rec[k], int):
            errs.append("%s: field %r must be an int or null, got %r"
                        % (where, k, rec[k]))
    if rec.get("commit") is not None and not isinstance(rec["commit"], str):
        errs.append("%s: field 'commit' must be a string or null"
                    % where)
    return errs


def _round_of(source: str) -> Optional[int]:
    m = re.search(r"_r(\d+)", os.path.basename(source))
    return int(m.group(1)) if m else None


# --------------------------------------------------------------------------
# artifact normalization

def _is_wrapper(blob: dict) -> bool:
    """Driver wrapper: {"n": .., "cmd": .., "rc": .., "tail": ..,
    "parsed": {...}} around the raw bench line."""
    return isinstance(blob, dict) and "tail" in blob and "rc" in blob \
        and "metric" not in blob and "n_devices" not in blob


def _is_multichip(blob: dict) -> bool:
    return isinstance(blob, dict) and "n_devices" in blob


def _num(p: dict, key: str):
    v = p.get(key)
    if isinstance(v, bool) or not isinstance(v, (int, float)) or \
            not math.isfinite(v):
        return None
    return v


def apply_breakdown_records(ab: dict, platform: str, source: str,
                            round_no=None, at_unix=None) -> List[dict]:
    """Normalize an `apply_breakdown` block (ISSUE 9: the close
    cockpit's per-op attribution) into direction-aware per-op records —
    per-op cost regressions gate against bench/history.jsonl exactly
    like every other metric."""
    out: List[dict] = []
    if not isinstance(ab, dict):
        return out
    v = _num(ab, "apply_wall_s")
    if v is not None:
        out.append(make_record("apply_wall_s", "s", v, platform, "lower",
                               source, round_no, at_unix))
    per_op = ab.get("per_op_ms")
    if isinstance(per_op, dict):
        for op, ms in sorted(per_op.items()):
            if _num({"v": ms}, "v") is None:
                continue
            out.append(make_record("apply_op_%s_ms" % op, "ms", ms,
                                   platform, "lower", source, round_no,
                                   at_unix))
    v = _num(ab, "other_ms")
    if v is not None:
        out.append(make_record("apply_other_ms", "ms", v, platform,
                               "lower", source, round_no, at_unix))
    return out


def validate_apply_breakdown(ab, where: str = "") -> List[str]:
    """Schema check for one `apply_breakdown` block (`check`/`--check`):
    the per-op components + residual must exist, be finite, and sum to
    the measured apply wall — a breakdown that silently stops adding up
    is itself a regression."""
    errs: List[str] = []
    if not isinstance(ab, dict):
        return ["%s: apply_breakdown is not an object: %r" % (where, ab)]
    wall = _num(ab, "apply_wall_s")
    if wall is None or wall < 0:
        errs.append("%s: apply_breakdown.apply_wall_s must be a finite "
                    "number >= 0, got %r" % (where, ab.get("apply_wall_s")))
    per_op = ab.get("per_op_ms")
    if not isinstance(per_op, dict):
        errs.append("%s: apply_breakdown.per_op_ms must be an object"
                    % where)
        per_op = {}
    for op, ms in per_op.items():
        if not isinstance(op, str) or _num({"v": ms}, "v") is None:
            errs.append("%s: apply_breakdown.per_op_ms[%r] must be a "
                        "finite number, got %r" % (where, op, ms))
    other = _num(ab, "other_ms")
    if other is None:
        errs.append("%s: apply_breakdown.other_ms must be a finite number"
                    % where)
    for key in ("closes", "bails", "state_reads"):
        if not isinstance(ab.get(key), dict):
            errs.append("%s: apply_breakdown.%s must be an object"
                        % (where, key))
    if wall is not None and other is not None and not errs:
        total_ms = sum(v for v in per_op.values()
                       if isinstance(v, (int, float))) + other
        # per-op values are rounded to 1 µs in the artifact; allow the
        # accumulated rounding slack plus a 0.1% relative band
        tol = max(1.0, 1e-3 * wall * 1e3)
        if abs(total_ms - wall * 1e3) > tol:
            errs.append(
                "%s: apply_breakdown parts sum to %.3f ms but "
                "apply_wall_s is %.3f ms — the breakdown no longer "
                "accounts for the measured wall" % (where, total_ms,
                                                    wall * 1e3))
    return errs


def overlay_breakdown_records(ob: dict, platform: str, source: str,
                              round_no=None, at_unix=None) -> List[dict]:
    """Normalize an `overlay_breakdown` block (ISSUE 10: the wire
    cockpit's fleet aggregate) into direction-aware records — the flood
    duplication ratio (the O(n²) flood waste ROADMAP item 3 wants to
    shrink) and the end-to-end tx latency gate against
    bench/history.jsonl exactly like every other metric. Latency
    records are only emitted when the run actually applied tracked
    transactions: a 0-valued p95 from an idle run must never become the
    committed best baseline."""
    out: List[dict] = []
    if not isinstance(ob, dict):
        return out
    fl = ob.get("flood")
    if isinstance(fl, dict) and _num(fl, "unique") and \
            _num(fl, "duplication_ratio") is not None:
        out.append(make_record("flood_duplication_ratio", "x",
                               fl["duplication_ratio"], platform, "lower",
                               source, round_no, at_unix))
    tx = ob.get("tx_latency_ms")
    if isinstance(tx, dict) and _num(tx, "count"):
        for q in ("p50", "p95"):
            v = _num(tx, q)
            if v is not None:
                out.append(make_record(
                    "tx_latency_total_%s_ms" % q, "ms", v, platform,
                    "lower", source, round_no, at_unix))
    return out


def validate_overlay_breakdown(ob, where: str = "") -> List[str]:
    """Schema check for one `overlay_breakdown` block (`check`/
    `--check`): bandwidth totals, flood dedup (ratio consistent with
    duplicates/unique) and the tx-lifecycle sum contract (stage seconds
    sum to total_seconds) must all hold — a breakdown that silently
    stops adding up is itself a regression."""
    errs: List[str] = []
    if not isinstance(ob, dict):
        return ["%s: overlay_breakdown is not an object: %r" % (where, ob)]
    for key in ("recv_bytes", "send_bytes", "recv_msgs", "send_msgs"):
        v = _num(ob, key)
        if v is None or v < 0:
            errs.append("%s: overlay_breakdown.%s must be a finite "
                        "number >= 0, got %r" % (where, key, ob.get(key)))
    fl = ob.get("flood")
    if not isinstance(fl, dict):
        errs.append("%s: overlay_breakdown.flood must be an object"
                    % where)
    else:
        u, d = _num(fl, "unique"), _num(fl, "duplicates")
        r = _num(fl, "duplication_ratio")
        if u is None or u < 0 or d is None or d < 0 or r is None or r < 0:
            errs.append("%s: overlay_breakdown.flood needs finite "
                        "unique/duplicates/duplication_ratio >= 0, got %r"
                        % (where, fl))
        elif u and abs(r - d / u) > 1e-3:
            errs.append("%s: overlay_breakdown.flood duplication_ratio "
                        "%.4f inconsistent with duplicates/unique %.4f"
                        % (where, r, d / u))
    tx = ob.get("tx_latency_ms")
    if not isinstance(tx, dict) or _num(tx, "count") is None:
        errs.append("%s: overlay_breakdown.tx_latency_ms must be an "
                    "object with a finite count" % where)
    else:
        p50, p95 = _num(tx, "p50"), _num(tx, "p95")
        if p50 is None or p95 is None or p50 < 0 or p95 + 1e-9 < p50:
            errs.append("%s: overlay_breakdown.tx_latency_ms needs "
                        "finite 0 <= p50 <= p95, got %r" % (where, tx))
    stage = ob.get("stage_seconds")
    total = _num(ob, "total_seconds")
    if not isinstance(stage, dict) or total is None or total < 0:
        errs.append("%s: overlay_breakdown needs stage_seconds (object) "
                    "and finite total_seconds >= 0" % where)
    else:
        bad = [s for s, v in stage.items()
               if _num({"v": v}, "v") is None]
        if bad:
            errs.append("%s: overlay_breakdown.stage_seconds has "
                        "non-finite entries %r" % (where, bad))
        else:
            # the tx-lifecycle sum contract: per-tx totals are computed
            # as the sum of the stage durations, so the cumulative
            # aggregates must agree to rounding slack
            s = sum(stage.values())
            tol = max(1e-6, 1e-3 * total)
            if abs(s - total) > tol:
                errs.append(
                    "%s: overlay_breakdown stage_seconds sum to %.6f s "
                    "but total_seconds is %.6f s — the lifecycle "
                    "breakdown no longer accounts for the total"
                    % (where, s, total))
    return errs


def fleet_verify_records(fv: dict, source: str, round_no=None,
                         at_unix=None) -> List[dict]:
    """Normalize a `fleet_verify` block (ISSUE 11: the multi-device
    verify leg) into direction-aware records keyed per forced device
    count — `verify-fleet-cpu<N>` platforms only ever gate against
    their own device-count history, never against single-chip device
    numbers."""
    out: List[dict] = []
    if not isinstance(fv, dict):
        return out
    for nd, leg in sorted(fv.items()):
        if not isinstance(leg, dict):
            continue
        plat = "verify-fleet-cpu%s" % nd
        for key, metric, unit, direction in (
                ("fleet_sigs_per_s", "fleet_sigs_per_s", "sigs/s",
                 "higher"),
                ("per_device_sigs_per_s", "per_device_sigs_per_s",
                 "sigs/s", "higher"),
                ("warm_restart_s", "warm_restart_s", "s", "lower")):
            v = _num(leg, key)
            if v is not None:
                out.append(make_record(metric, unit, v, plat, direction,
                                       source, round_no, at_unix))
    return out


def validate_fleet_verify(fv, where: str = "") -> List[str]:
    """Schema check for one `fleet_verify` block (`check`/`--check`):
    every device-count leg needs finite positive rates whose
    per-device figure is exactly fleet/devices, a non-negative warm
    restart, and a device count matching its key — a fleet artifact
    whose arithmetic stops agreeing is itself a regression."""
    errs: List[str] = []
    if not isinstance(fv, dict):
        return ["%s: fleet_verify is not an object: %r" % (where, fv)]
    for nd, leg in sorted(fv.items()):
        lw = "%s: fleet_verify[%s]" % (where, nd)
        if not isinstance(leg, dict):
            errs.append("%s must be an object" % lw)
            continue
        devices = leg.get("devices")
        if not isinstance(devices, int) or isinstance(devices, bool) \
                or devices < 1 or str(devices) != str(nd):
            errs.append("%s.devices must be a positive int matching its "
                        "key, got %r" % (lw, devices))
            continue
        fleet = _num(leg, "fleet_sigs_per_s")
        per_dev = _num(leg, "per_device_sigs_per_s")
        if fleet is None or fleet <= 0:
            errs.append("%s.fleet_sigs_per_s must be a finite number "
                        "> 0, got %r" % (lw, leg.get("fleet_sigs_per_s")))
        if per_dev is None or per_dev <= 0:
            errs.append("%s.per_device_sigs_per_s must be a finite "
                        "number > 0, got %r"
                        % (lw, leg.get("per_device_sigs_per_s")))
        if fleet is not None and per_dev is not None and fleet > 0:
            want = fleet / devices
            if abs(per_dev - want) > max(0.15, 1e-3 * want):
                errs.append("%s.per_device_sigs_per_s %.1f inconsistent "
                            "with fleet/devices %.1f" % (lw, per_dev,
                                                         want))
        wr = _num(leg, "warm_restart_s")
        if wr is None or wr < 0:
            errs.append("%s.warm_restart_s must be a finite number >= 0,"
                        " got %r" % (lw, leg.get("warm_restart_s")))
    return errs


def hash_bench_records(hb: dict, source: str, round_no=None,
                       at_unix=None) -> List[dict]:
    """Normalize a `hash_bench` block (ISSUE 12: the batched-SHA-256
    leg) into direction-aware records — kernel throughput per
    (lanes × blocks) shape keyed under `hash-<platform>-<shape>`
    platforms (a jax-on-CPU leg only ever gates against its own CPU
    history, never against real-device numbers), the host hashlib
    baseline under `hash-host`, and the checkpoint proof-size /
    light-client verify-cost headlines under `checkpoint-cpu`."""
    out: List[dict] = []
    if not isinstance(hb, dict):
        return out
    kernel = hb.get("kernel")
    if isinstance(kernel, dict):
        for shape, leg in sorted(kernel.items()):
            if not isinstance(leg, dict):
                continue
            plat = "hash-%s-%s" % (leg.get("platform", "cpu"), shape)
            for key, unit in (("hash_bytes_per_s", "bytes/s"),
                              ("hash_msgs_per_s", "msgs/s")):
                v = _num(leg, key)
                if v is not None:
                    out.append(make_record(key, unit, v, plat, "higher",
                                           source, round_no, at_unix))
    host = hb.get("host")
    if isinstance(host, dict):
        v = _num(host, "hash_bytes_per_s")
        if v is not None:
            out.append(make_record("hash_bytes_per_s", "bytes/s", v,
                                   "hash-host", "higher", source,
                                   round_no, at_unix))
    cp = hb.get("checkpoint")
    if isinstance(cp, dict):
        for key, metric, unit in (
                ("proof_bytes", "checkpoint_proof_bytes", "bytes"),
                ("verify_p95_ms", "checkpoint_verify_ms", "ms"),
                ("update_p95_ms", "checkpoint_update_ms", "ms")):
            v = _num(cp, key)
            if v is not None:
                out.append(make_record(metric, unit, v, "checkpoint-cpu",
                                       "lower", source, round_no,
                                       at_unix))
    return out


def validate_hash_bench(hb, where: str = "") -> List[str]:
    """Schema check for one `hash_bench` block (`check`/`--check`):
    every kernel shape leg needs finite positive rates consistent with
    each other, the checkpoint block needs a positive proof size,
    ordered verify percentiles and a TRUE oracle-equality flag — a
    hashing artifact whose own differential oracle failed must never
    read as a committed baseline."""
    errs: List[str] = []
    if not isinstance(hb, dict):
        return ["%s: hash_bench is not an object: %r" % (where, hb)]
    kernel = hb.get("kernel")
    if not isinstance(kernel, dict) or not kernel:
        errs.append("%s: hash_bench.kernel must be a non-empty object"
                    % where)
        kernel = {}
    for shape, leg in sorted(kernel.items()):
        lw = "%s: hash_bench.kernel[%s]" % (where, shape)
        if not isinstance(leg, dict):
            errs.append("%s must be an object" % lw)
            continue
        bps = _num(leg, "hash_bytes_per_s")
        mps = _num(leg, "hash_msgs_per_s")
        mb = _num(leg, "msg_bytes")
        if bps is None or bps <= 0:
            errs.append("%s.hash_bytes_per_s must be a finite number "
                        "> 0, got %r" % (lw, leg.get("hash_bytes_per_s")))
        if mps is None or mps <= 0:
            errs.append("%s.hash_msgs_per_s must be a finite number "
                        "> 0, got %r" % (lw, leg.get("hash_msgs_per_s")))
        if None not in (bps, mps, mb) and mps > 0 and mb > 0:
            want = mps * mb
            if abs(bps - want) > max(1.0, 1e-2 * want):
                errs.append("%s.hash_bytes_per_s %.1f inconsistent with "
                            "msgs/s * msg_bytes %.1f" % (lw, bps, want))
    cp = hb.get("checkpoint")
    if not isinstance(cp, dict):
        errs.append("%s: hash_bench.checkpoint must be an object" % where)
    else:
        pb = _num(cp, "proof_bytes")
        if pb is None or pb <= 0:
            errs.append("%s: hash_bench.checkpoint.proof_bytes must be "
                        "a finite number > 0, got %r"
                        % (where, cp.get("proof_bytes")))
        p50, p95 = _num(cp, "verify_p50_ms"), _num(cp, "verify_p95_ms")
        if p50 is None or p95 is None or p50 < 0 or p95 + 1e-9 < p50:
            errs.append("%s: hash_bench.checkpoint needs finite "
                        "0 <= verify_p50_ms <= verify_p95_ms, got %r"
                        % (where, cp))
        if cp.get("oracle_equal") is not True:
            errs.append("%s: hash_bench.checkpoint.oracle_equal must be "
                        "true — the incremental Merkle root diverged "
                        "from the from-scratch oracle in this artifact"
                        % where)
    return errs


def bucketdb_records(bd: dict, source: str, round_no=None,
                     at_unix=None) -> List[dict]:
    """Normalize a `bucketdb_bench` block (ISSUE 14: the
    million-account bucket-backed read gate) into direction-aware
    records under the `bucketdb-cpu` platform: the latency-flatness
    ratio and large-scale close p50 (lower is better), the surge
    prefetch hit-rate (higher), and the bloom false-positive rate
    (lower)."""
    out: List[dict] = []
    if not isinstance(bd, dict):
        return out
    for key, metric, unit, direction in (
            ("latency_ratio", "bucketdb_latency_ratio", "x", "lower"),
            ("prefetch_hit_rate_pct", "bucketdb_prefetch_hit_rate_pct",
             "pct", "higher"),
            ("bloom_fp_pct", "bucketdb_bloom_fp_pct", "pct", "lower")):
        v = _num(bd, key)
        if v is not None:
            out.append(make_record(metric, unit, v, "bucketdb-cpu",
                                   direction, source, round_no, at_unix))
    large = bd.get("large")
    if isinstance(large, dict):
        v = _num(large, "close_ms_p50")
        if v is not None:
            out.append(make_record("bucketdb_close_large_p50_ms", "ms",
                                   v, "bucketdb-cpu", "lower", source,
                                   round_no, at_unix))
    return out


def validate_bucketdb(bd, where: str = "") -> List[str]:
    """Schema check for one `bucketdb_bench` block (`check`/`--check`):
    both scale legs must exist with finite positive close latencies and
    a strictly larger `large` account count; the recorded
    latency-flatness ratio must actually be the legs' p50 ratio AND
    within the 1.25x acceptance gate; the surge prefetch hit-rate must
    hold >= 95%, the bloom false-positive rate <= 5%, and the
    cockpit-asserted apply-path SQL point-lookup count must be ZERO — a
    committed million-account artifact that fails its own gates is a
    broken baseline, not a measurement."""
    errs: List[str] = []
    if not isinstance(bd, dict):
        return ["%s: bucketdb_bench is not an object: %r" % (where, bd)]
    legs = {}
    for name in ("small", "large"):
        leg = bd.get(name)
        if not isinstance(leg, dict):
            errs.append("%s: bucketdb_bench.%s must be an object"
                        % (where, name))
            continue
        acc = _num(leg, "accounts")
        p50 = _num(leg, "close_ms_p50")
        if acc is None or acc <= 0:
            errs.append("%s: bucketdb_bench.%s.accounts must be a finite "
                        "number > 0, got %r" % (where, name,
                                                leg.get("accounts")))
        if p50 is None or p50 <= 0:
            errs.append("%s: bucketdb_bench.%s.close_ms_p50 must be a "
                        "finite number > 0, got %r"
                        % (where, name, leg.get("close_ms_p50")))
        legs[name] = leg
    if len(legs) == 2 and not errs:
        if legs["large"]["accounts"] <= legs["small"]["accounts"]:
            errs.append("%s: bucketdb_bench.large.accounts must exceed "
                        "small.accounts" % where)
        ratio = _num(bd, "latency_ratio")
        want = legs["large"]["close_ms_p50"] / legs["small"]["close_ms_p50"]
        if ratio is None:
            errs.append("%s: bucketdb_bench.latency_ratio must be a "
                        "finite number" % where)
        else:
            if abs(ratio - want) > max(0.01, 0.01 * want):
                errs.append("%s: bucketdb_bench.latency_ratio %.4f != "
                            "large/small p50 ratio %.4f"
                            % (where, ratio, want))
            if ratio > 1.25:
                errs.append("%s: bucketdb_bench.latency_ratio %.4f "
                            "exceeds the 1.25x flatness gate"
                            % (where, ratio))
    hit = _num(bd, "prefetch_hit_rate_pct")
    if hit is None or hit < 95.0 or hit > 100.0:
        errs.append("%s: bucketdb_bench.prefetch_hit_rate_pct must be in "
                    "[95, 100], got %r"
                    % (where, bd.get("prefetch_hit_rate_pct")))
    fp = _num(bd, "bloom_fp_pct")
    if fp is None or fp < 0.0 or fp > 5.0:
        errs.append("%s: bucketdb_bench.bloom_fp_pct must be in [0, 5], "
                    "got %r" % (where, bd.get("bloom_fp_pct")))
    sql = bd.get("sql_point_lookups")
    if sql != 0:
        errs.append("%s: bucketdb_bench.sql_point_lookups must be 0 "
                    "(the zero-SQL apply-path gate), got %r"
                    % (where, sql))
    return errs


def propagation_records(pb: dict, platform: str, source: str,
                        round_no=None, at_unix=None) -> List[dict]:
    """Normalize a `propagation` block (ISSUE 17: the propagation
    cockpit's fleet-merged relay trees) into direction-aware records:
    hop latency and tree depth percentiles over the reconstructed
    first-delivery spanning trees (lower), the redundant bandwidth
    share — the fraction of flooded bytes that arrived as duplicate
    edges, the O(n²) waste a structured relay would reclaim (lower) —
    and the worst per-peer usefulness score (higher; a peer that only
    ever sends duplicates is pure overhead)."""
    out: List[dict] = []
    if not isinstance(pb, dict) or not _num(pb, "trees"):
        return out
    for key, metric, unit in (
            ("hop_latency_p50_ms", "prop_hop_latency_p50_ms", "ms"),
            ("hop_latency_p95_ms", "prop_hop_latency_p95_ms", "ms"),
            ("tree_depth_p95", "prop_tree_depth_p95", "hops"),
            ("redundant_bandwidth_share",
             "prop_redundant_bandwidth_share", "share")):
        v = _num(pb, key)
        if v is not None:
            out.append(make_record(metric, unit, v, platform, "lower",
                                   source, round_no, at_unix))
    peers = pb.get("peers")
    if isinstance(peers, dict):
        v = _num(peers, "worst_usefulness")
        if v is not None:
            out.append(make_record("prop_worst_peer_usefulness", "share",
                                   v, platform, "higher", source,
                                   round_no, at_unix))
    return out


def validate_propagation(pb, where: str = "", flood=None) -> List[str]:
    """Schema check for one `propagation` block (`check`/`--check`):
    hop/byte totals must be finite and non-negative, the recorded
    redundant share must actually be wasted/flooded bytes, percentiles
    must be ordered — and when the sibling wire cockpit's `flood` block
    is available, duplicates/firsts over the merged hop records must
    reconcile with its duplication ratio within 10% relative tolerance
    (both cockpits count the same Floodgate.add_record receipts, so a
    drift between them means hop attribution lost edges)."""
    errs: List[str] = []
    if not isinstance(pb, dict):
        return ["%s: propagation is not an object: %r" % (where, pb)]
    trees = pb.get("trees")
    if not isinstance(trees, int) or isinstance(trees, bool) or trees < 0:
        errs.append("%s: propagation.trees must be an int >= 0, got %r"
                    % (where, trees))
    vals = {}
    for key in ("firsts", "duplicates", "flood_bytes", "wasted_bytes"):
        v = _num(pb, key)
        if v is None or v < 0:
            errs.append("%s: propagation.%s must be a finite number "
                        ">= 0, got %r" % (where, key, pb.get(key)))
        vals[key] = v
    share = _num(pb, "redundant_bandwidth_share")
    if share is None or share < 0 or share > 1:
        errs.append("%s: propagation.redundant_bandwidth_share must be "
                    "in [0, 1], got %r"
                    % (where, pb.get("redundant_bandwidth_share")))
    elif vals.get("flood_bytes"):
        want = vals["wasted_bytes"] / vals["flood_bytes"]
        if abs(share - want) > max(1e-3, 0.01 * want):
            errs.append("%s: propagation.redundant_bandwidth_share %.4f "
                        "!= wasted/flooded bytes %.4f" % (where, share,
                                                          want))
    p50 = _num(pb, "hop_latency_p50_ms")
    p95 = _num(pb, "hop_latency_p95_ms")
    if p50 is None or p95 is None or p50 < 0 or p95 + 1e-9 < p50:
        errs.append("%s: propagation needs finite "
                    "0 <= hop_latency_p50_ms <= hop_latency_p95_ms, "
                    "got p50=%r p95=%r" % (where,
                                           pb.get("hop_latency_p50_ms"),
                                           pb.get("hop_latency_p95_ms")))
    depth = _num(pb, "tree_depth_p95")
    if depth is None or depth < 0:
        errs.append("%s: propagation.tree_depth_p95 must be a finite "
                    "number >= 0, got %r"
                    % (where, pb.get("tree_depth_p95")))
    peers = pb.get("peers")
    if isinstance(peers, dict):
        wu = peers.get("worst_usefulness")
        if wu is not None and (_num(peers, "worst_usefulness") is None or
                               wu < 0 or wu > 1):
            errs.append("%s: propagation.peers.worst_usefulness must be "
                        "in [0, 1] or null, got %r" % (where, wu))
    # cross-cockpit reconciliation against the wire cockpit's dedup
    # accounting (ISSUE 17 acceptance gate)
    if isinstance(flood, dict) and vals.get("firsts"):
        r = _num(flood, "duplication_ratio")
        if r is not None and r >= 0:
            derived = vals["duplicates"] / vals["firsts"]
            if abs(derived - r) > max(0.05, 0.10 * r):
                errs.append(
                    "%s: propagation duplicates/firsts %.4f does not "
                    "reconcile with flood duplication_ratio %.4f within "
                    "10%% — hop records and flood dedup have drifted "
                    "apart" % (where, derived, r))
    return errs


def ingress_records(ib: dict, platform: str, source: str,
                    round_no=None, at_unix=None) -> List[dict]:
    """Normalize an `ingress` block (ISSUE 18: the admission-tier
    overload leg) into direction-aware records: priority-class goodput
    under overload (higher — the tier's whole point), the shed ratio
    (higher: under a fixed oversubscription, shedding MORE junk at
    admission is the desired behavior — a falling shed ratio means junk
    is leaking into the pool), applied-tx latency p95 (lower), and its
    ratio against the unloaded baseline (lower; the 2x acceptance
    gate)."""
    out: List[dict] = []
    if not isinstance(ib, dict) or not _num(ib, "decided"):
        return out
    pri = ib.get("priority")
    if isinstance(pri, dict):
        v = _num(pri, "goodput")
        if v is not None:
            out.append(make_record("ingress_priority_goodput", "share",
                                   v, platform, "higher", source,
                                   round_no, at_unix))
    for key, metric, unit, direction in (
            ("shed_ratio", "ingress_shed_ratio", "share", "higher"),
            ("tx_latency_p95_ms", "ingress_tx_latency_p95_ms", "ms",
             "lower"),
            ("p95_ratio", "ingress_p95_vs_unloaded_ratio", "x",
             "lower")):
        v = _num(ib, key)
        if v is not None:
            out.append(make_record(metric, unit, v, platform, direction,
                                   source, round_no, at_unix))
    return out


def validate_ingress(ib, where: str = "") -> List[str]:
    """Schema check for one `ingress` block (`check`/`--check`): the
    admission counters must be non-negative ints with the shed ratio
    actually shed/decided, priority goodput must be applied/submitted in
    [0, 1], the p95 ratio must be its own numerator/denominator, the
    intake/source occupancies must respect their declared caps (the
    bounded-memory acceptance gate travels with the artifact), and the
    lifecycle funnel's shed/throttled outcomes can never exceed the
    ingress tier's own decision counts (the funnel tracks first-seen
    txs only)."""
    errs: List[str] = []
    if not isinstance(ib, dict):
        return ["%s: ingress is not an object: %r" % (where, ib)]
    vals = {}
    for key in ("decided", "admitted", "throttled", "shed"):
        v = ib.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append("%s: ingress.%s must be an int >= 0, got %r"
                        % (where, key, v))
            v = None
        vals[key] = v
    if None not in vals.values() and \
            vals["decided"] != vals["admitted"] + vals["throttled"] + \
            vals["shed"]:
        errs.append("%s: ingress.decided %d != admitted+throttled+shed %d"
                    % (where, vals["decided"],
                       vals["admitted"] + vals["throttled"] + vals["shed"]))
    ratio = _num(ib, "shed_ratio")
    if ratio is None or ratio < 0 or ratio > 1:
        errs.append("%s: ingress.shed_ratio must be in [0, 1], got %r"
                    % (where, ib.get("shed_ratio")))
    elif vals.get("decided"):
        want = vals["shed"] / vals["decided"] if vals.get("shed") \
            is not None else None
        if want is not None and abs(ratio - want) > max(1e-3, 0.01 * want):
            errs.append("%s: ingress.shed_ratio %.4f != shed/decided %.4f"
                        % (where, ratio, want))
    pri = ib.get("priority")
    if not isinstance(pri, dict):
        errs.append("%s: ingress.priority must be an object, got %r"
                    % (where, pri))
    else:
        sub, app = pri.get("submitted"), pri.get("applied")
        gp = _num(pri, "goodput")
        if not isinstance(sub, int) or not isinstance(app, int) or \
                isinstance(sub, bool) or isinstance(app, bool) or \
                sub < 0 or app < 0 or app > sub:
            errs.append("%s: ingress.priority needs ints "
                        "0 <= applied <= submitted, got %r/%r"
                        % (where, app, sub))
        elif gp is None or gp < 0 or gp > 1:
            errs.append("%s: ingress.priority.goodput must be in [0, 1], "
                        "got %r" % (where, pri.get("goodput")))
        elif sub and abs(gp - app / sub) > max(1e-3, 0.01 * (app / sub)):
            errs.append("%s: ingress.priority.goodput %.4f != "
                        "applied/submitted %.4f" % (where, gp, app / sub))
    p95 = _num(ib, "tx_latency_p95_ms")
    base = _num(ib, "unloaded_p95_ms")
    pr = _num(ib, "p95_ratio")
    if p95 is None or p95 < 0 or base is None or base <= 0 or \
            pr is None or pr < 0:
        errs.append("%s: ingress needs finite tx_latency_p95_ms >= 0, "
                    "unloaded_p95_ms > 0, p95_ratio >= 0; got %r/%r/%r"
                    % (where, ib.get("tx_latency_p95_ms"),
                       ib.get("unloaded_p95_ms"), ib.get("p95_ratio")))
    elif abs(pr - p95 / base) > max(0.01, 0.01 * pr):
        errs.append("%s: ingress.p95_ratio %.3f != p95/unloaded %.3f"
                    % (where, pr, p95 / base))
    # bounded-memory gate: occupancy <= cap for the intake and the
    # per-source tracking map
    for blk, occ_key in (("intake", "depth"), ("sources", "tracked")):
        sub = ib.get(blk)
        if not isinstance(sub, dict):
            errs.append("%s: ingress.%s must be an object, got %r"
                        % (where, blk, sub))
            continue
        occ, cap = _num(sub, occ_key), _num(sub, "cap")
        if occ is None or cap is None or occ < 0 or cap <= 0:
            errs.append("%s: ingress.%s needs finite %s >= 0 and cap > 0,"
                        " got %r/%r" % (where, blk, occ_key,
                                        sub.get(occ_key), sub.get("cap")))
        elif occ > cap:
            errs.append("%s: ingress.%s.%s %.0f exceeds its cap %.0f — "
                        "an unbounded queue in a committed artifact"
                        % (where, blk, occ_key, occ, cap))
    outcomes = ib.get("outcomes")
    if isinstance(outcomes, dict):
        for kind in ("shed", "throttled"):
            oc = outcomes.get(kind, 0)
            lim = vals.get(kind)
            if isinstance(oc, int) and lim is not None and oc > lim:
                errs.append("%s: lifecycle outcome %s=%d exceeds the "
                            "ingress %s count %d" % (where, kind, oc,
                                                     kind, lim))
    return errs


def scp_records(sb: dict, platform: str, source: str,
                round_no=None, at_unix=None) -> List[dict]:
    """Normalize an `scp` block (ISSUE 19: the consensus cockpit) into
    direction-aware records: envelopes per externalized slot (lower —
    the committed O(n^2) flood baseline that ROADMAP item 1's BLS
    quorum certificates must beat) and the worst ballot round count
    (lower — round inflation is timer retries, not progress)."""
    out: List[dict] = []
    if not isinstance(sb, dict):
        return out
    v = _num(sb, "envelopes_per_slot")
    if v is not None:
        out.append(make_record("envelopes_per_slot", "envelopes", v,
                               platform, "lower", source, round_no,
                               at_unix))
    rounds = sb.get("rounds")
    if isinstance(rounds, dict):
        v = _num(rounds, "ballot")
        if v is not None:
            out.append(make_record("scp_ballot_rounds_worst", "rounds",
                                   v, platform, "lower", source,
                                   round_no, at_unix))
    return out


def footprint_records(fb: dict, platform: str, source: str,
                      round_no=None, at_unix=None) -> List[dict]:
    """Normalize a `footprint` block (ISSUE 19: the node footprint
    census) into direction-aware records: mean per-node RSS (lower —
    the N-vs-RSS scaling curve for the 100-node push)."""
    out: List[dict] = []
    if not isinstance(fb, dict):
        return out
    v = _num(fb, "per_node_rss_mb")
    if v is not None:
        out.append(make_record("per_node_rss_mb", "MB", v, platform,
                               "lower", source, round_no, at_unix))
    return out


def _check_phase_sum(phase_s, wall, lw: str, errs: List[str]) -> None:
    """Phase latencies telescope inside the slot: the sum of non-null
    per-phase seconds can never exceed the slot wall they partition."""
    if not isinstance(phase_s, dict):
        return
    total = 0.0
    for p, v in sorted(phase_s.items()):
        if v is None:
            continue
        pv = _num({"v": v}, "v")
        if pv is None or pv < 0:
            errs.append("%s: phase %r must be a finite number >= 0 or "
                        "null, got %r" % (lw, p, v))
            return
        total += pv
    if wall is not None and total > wall + max(1e-4, 1e-3 * wall):
        errs.append("%s: phase latencies sum to %.6f s but the slot "
                    "wall is %.6f s — phases cannot outlast the slot "
                    "they partition" % (lw, total, wall))


def validate_scp(sb, where: str = "") -> List[str]:
    """Schema check for an `scp` block (`check`/`--check`): phase
    latencies must telescope inside each slot wall and envelope counts
    must be sane non-negative numbers. Accepts both the fleet-merged
    `scp_summary()` shape and a per-node `ScpStats.fleet_json()` blob
    (keyed by the `self`/`totals` fields only the per-node shape has).
    The sum-vs-wall contract only binds per node: the fleet merge takes
    the per-PHASE worst case over nodes, and a sum of maxes can exceed
    the max wall — there the phases are only checked for sanity."""
    errs: List[str] = []
    if not isinstance(sb, dict):
        return ["%s: scp is not an object: %r" % (where, sb)]
    if "self" in sb or "totals" in sb:
        # per-node ScpStats.fleet_json()
        for slot_str, rec in sorted((sb.get("slots") or {}).items()):
            lw = "%s: scp.slots[%s]" % (where, slot_str)
            if not isinstance(rec, dict):
                errs.append("%s must be an object" % lw)
                continue
            ph = rec.get("phases")
            if isinstance(ph, dict):
                _check_phase_sum(ph.get("phase_s"), _num(ph, "wall_s"),
                                 lw, errs)
        return errs
    # fleet-merged scp_summary()
    eps = _num(sb, "envelopes_per_slot")
    if eps is None or eps < 0:
        errs.append("%s: scp.envelopes_per_slot must be a finite number"
                    " >= 0, got %r" % (where, sb.get("envelopes_per_slot")))
    for slot_str, rec in sorted((sb.get("slots") or {}).items()):
        lw = "%s: scp.slots[%s]" % (where, slot_str)
        if not isinstance(rec, dict):
            errs.append("%s must be an object" % lw)
            continue
        env = rec.get("envelopes")
        if not isinstance(env, int) or isinstance(env, bool) or env < 0:
            errs.append("%s.envelopes must be an int >= 0, got %r"
                        % (lw, env))
        # per-phase maxes over nodes: sanity only, no sum-vs-wall bound
        _check_phase_sum(rec.get("phase_s"), None, lw, errs)
        wall = _num(rec, "wall_s")
        if rec.get("wall_s") is not None and (wall is None or wall < 0):
            errs.append("%s.wall_s must be a finite number >= 0, got %r"
                        % (lw, rec.get("wall_s")))
    return errs


def _check_footprint_structs(structs, lw: str, errs: List[str]) -> None:
    if not isinstance(structs, dict):
        errs.append("%s.structs must be an object, got %r"
                    % (lw, structs))
        return
    for sname, entry in sorted(structs.items()):
        if not isinstance(entry, dict):
            errs.append("%s.structs[%s] must be an object" % (lw, sname))
            continue
        if entry.get("error") is not None:
            continue    # scrape-time callback failure; occupancy unknown
        occ, cap = _num(entry, "occupancy"), _num(entry, "capacity")
        if occ is None or cap is None or occ < 0 or cap <= 0:
            errs.append("%s.structs[%s] needs finite occupancy >= 0 and"
                        " capacity > 0, got %r/%r"
                        % (lw, sname, entry.get("occupancy"),
                           entry.get("capacity")))
        elif occ > cap:
            errs.append("%s.structs[%s] occupancy %.0f exceeds its "
                        "capacity %.0f — an unbounded structure in a "
                        "committed artifact" % (lw, sname, occ, cap))


def validate_footprint(fb, where: str = "") -> List[str]:
    """Schema check for a `footprint` block (`check`/`--check`): every
    registered bounded structure must respect its declared capacity —
    the bounded-memory gate travels with the artifact. Accepts both the
    fleet-merged `footprint_table()` shape and a per-node census
    (`BoundedStructRegistry.to_json()`, keyed by its `structs` field).
    """
    errs: List[str] = []
    if not isinstance(fb, dict):
        return ["%s: footprint is not an object: %r" % (where, fb)]
    if "structs" in fb:
        # per-node census
        _check_footprint_structs(fb["structs"], "%s: footprint" % where,
                                 errs)
        oc = fb.get("over_capacity")
        if oc:
            errs.append("%s: footprint.over_capacity is non-empty (%s)"
                        % (where, ", ".join(sorted(oc))))
        return errs
    # fleet-merged footprint_table()
    v = _num(fb, "per_node_rss_mb")
    if v is None or v < 0:
        errs.append("%s: footprint.per_node_rss_mb must be a finite "
                    "number >= 0, got %r"
                    % (where, fb.get("per_node_rss_mb")))
    over = fb.get("over_capacity")
    if isinstance(over, dict):
        for node, names in sorted(over.items()):
            errs.append("%s: footprint.over_capacity[%s] lists %s — a "
                        "bounded structure overran its cap in a "
                        "committed artifact"
                        % (where, node, ", ".join(sorted(names))))
    for node, nb in sorted((fb.get("per_node") or {}).items()):
        if not isinstance(nb, dict):
            errs.append("%s: footprint.per_node[%s] must be an object"
                        % (where, node))
            continue
        _check_footprint_structs(nb.get("structs"),
                                 "%s: footprint.per_node[%s]"
                                 % (where, node), errs)
    return errs


def _replay_leg_records(leg: dict, platform: str, source: str,
                        round_no, at_unix) -> List[dict]:
    out = []
    for key, metric, unit, direction in (
            ("ledgers_per_sec", "replay_ledgers_per_sec", "ledgers/s",
             "higher"),
            ("txs_per_sec", "replay_txs_per_sec", "txs/s", "higher"),
            ("crypto_s", "replay_crypto_s", "s", "lower"),
            ("apply_s", "replay_apply_s", "s", "lower")):
        v = _num(leg, key)
        if v is not None:
            out.append(make_record(metric, unit, v, platform, direction,
                                   source, round_no, at_unix))
    out.extend(apply_breakdown_records(leg.get("apply_breakdown"),
                                       platform, source, round_no, at_unix))
    return out


def _payload_records(p: dict, source: str, round_no,
                     at_unix=None) -> List[dict]:
    """Normalize one bench-output payload (the raw `bench.py` JSON line,
    or a nested last_device / last_real_device_result block)."""
    out: List[dict] = []
    at_unix = p.get("at_unix", at_unix)
    if not isinstance(at_unix, int):
        at_unix = None
    platform = p.get("platform") or "unknown"

    def rec(metric, unit, value, plat, direction):
        out.append(make_record(metric, unit, value, plat, direction,
                               source, round_no, at_unix))

    if isinstance(p.get("metric"), str) and _num(p, "value") is not None \
            and isinstance(p.get("unit"), str):
        rec(p["metric"], p["unit"], p["value"], platform, "higher")
    v = _num(p, "cpu_openssl_baseline_sigs_per_sec")
    if v is not None:
        rec("cpu_openssl_baseline_sigs_per_sec", "sigs/s", v,
            "openssl-cpu", "higher")
    if platform in _DEVICE_PLATFORMS:
        for key, metric in (("compile_s", "device_compile_cold_s"),
                            ("compile_warm_s", "device_compile_warm_s"),
                            ("init_s", "device_init_s"),
                            ("latency128_p50_ms", "verify_latency128_p50_ms"),
                            ("latency128_p99_ms", "verify_latency128_p99_ms")):
            v = _num(p, key)
            if v is not None:
                rec(metric, "ms" if metric.endswith("_ms") else "s", v,
                    platform, "lower")
        # warm-restart trajectory (recorded from ISSUE 6 on): per-bucket
        # AOT warmup seconds through the verifier's cockpit
        wb = p.get("warmup_buckets_s")
        if isinstance(wb, dict) and wb:
            total = 0.0
            for b, secs in sorted(wb.items()):
                if _num({"v": secs}, "v") is None:
                    continue
                rec("warmup_bucket_%s_s" % b, "s", secs, platform, "lower")
                total += secs
            rec("warmup_total_s", "s", round(total, 3), platform, "lower")
    rep = p.get("replay")
    if isinstance(rep, dict):
        for leg_name in ("cpu", "tpu"):
            leg = rep.get(leg_name)
            if isinstance(leg, dict):
                out.extend(_replay_leg_records(
                    leg, leg.get("backend", leg_name), source, round_no,
                    at_unix))
    for key, metric, plat in (
            ("replay_speedup", "replay_speedup", "tpu-vs-cpu"),
            ("replay_crypto_speedup", "replay_crypto_speedup",
             "tpu-vs-cpu")):
        v = _num(p, key)
        if v is not None:
            rec(metric, "x", v, plat, "higher")
    ra = p.get("replay_apply")
    if isinstance(ra, dict):
        for leg_name in ("native", "python"):
            leg = ra.get(leg_name)
            if isinstance(leg, dict):
                out.extend(_replay_leg_records(
                    leg, "cpu-apply-%s" % leg_name, source, round_no,
                    at_unix))
        v = _num(ra, "apply_speedup")
        if v is not None:
            rec("native_apply_speedup", "x", v, "cpu", "higher")
    # wire-cockpit records from a payload-level overlay_breakdown
    # (`bench.py --fleet`; scenario artifacts embed theirs in an
    # explicit `records` list, which normalize_any prefers)
    ob = p.get("overlay_breakdown")
    if isinstance(ob, dict):
        out.extend(overlay_breakdown_records(ob, platform, source,
                                             round_no, at_unix))
    # propagation-cockpit records from a payload-level `propagation`
    # block (`bench.py --fleet`; scenario artifacts embed theirs in an
    # explicit `records` list, which normalize_any prefers)
    pb = p.get("propagation")
    if isinstance(pb, dict):
        out.extend(propagation_records(pb, platform, source, round_no,
                                       at_unix))
    # consensus-cockpit + footprint-census records from payload-level
    # blocks (`bench.py --fleet-scale`; scale artifacts also carry an
    # explicit `records` list, which normalize_any prefers — this path
    # keeps nested/legacy blobs normalizable)
    sb = p.get("scp")
    if isinstance(sb, dict):
        out.extend(scp_records(sb, platform, source, round_no, at_unix))
    fb = p.get("footprint")
    if isinstance(fb, dict):
        out.extend(footprint_records(fb, platform, source, round_no,
                                     at_unix))
    # multi-device verify legs (`bench.py --fleet-verify`; the artifact
    # also carries an explicit `records` list, which normalize_any
    # prefers — this path keeps nested/legacy blobs normalizable)
    fv = p.get("fleet_verify")
    if isinstance(fv, dict):
        out.extend(fleet_verify_records(fv, source, round_no, at_unix))
        v = _num(p, "fleet_speedup")
        if v is not None:
            out.append(make_record("fleet_verify_speedup", "x", v,
                                   "verify-fleet-cpu", "higher", source,
                                   round_no, at_unix))
    # batched-hash legs (`bench.py --hash`; the artifact also carries
    # an explicit `records` list, which normalize_any prefers)
    hb = p.get("hash_bench")
    if isinstance(hb, dict):
        out.extend(hash_bench_records(hb, source, round_no, at_unix))
    # million-account BucketDB leg (`bench.py --bucketdb`; the artifact
    # also carries an explicit `records` list, which normalize_any
    # prefers — this path keeps nested/legacy blobs normalizable)
    bd = p.get("bucketdb_bench")
    if isinstance(bd, dict):
        out.extend(bucketdb_records(bd, source, round_no, at_unix))
    # device history survives device-less rounds via the cached block
    for nest in (p.get("last_device"),
                 (p.get("errors") or {}).get("last_real_device_result")):
        if isinstance(nest, dict):
            out.extend(_payload_records(nest, source, round_no, at_unix))
    return out


def records_from_bench(blob: dict, source: str) -> List[dict]:
    round_no = _round_of(source)
    payload = blob.get("parsed") if _is_wrapper(blob) else blob
    if not isinstance(payload, dict):
        return []
    return _payload_records(payload, source, round_no)


def records_from_multichip(blob: dict, source: str) -> List[dict]:
    if not blob.get("ok"):
        return []      # a failed run leaves no trajectory point
    return [make_record("multichip_devices", "devices",
                        blob.get("n_devices", 0), "axon", "higher",
                        source, _round_of(source))]


def normalize_any(blob, source: str) -> List[dict]:
    """Records from any supported blob shape: an explicit
    {"records": [...]} list (bench.py --compare output), a multichip
    driver record, or a bench payload/wrapper."""
    if isinstance(blob, dict) and isinstance(blob.get("records"), list):
        return list(blob["records"])
    if _is_multichip(blob):
        return records_from_multichip(blob, source)
    return records_from_bench(blob, source)


# --------------------------------------------------------------------------
# schema checks

def check_artifact(path: str) -> List[str]:
    name = os.path.basename(path)
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError as e:
        return ["%s: unreadable: %s" % (name, e)]
    if name.endswith(".jsonl"):
        errs: List[str] = []
        records = []
        for i, line in enumerate(text.splitlines(), 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                errs.append("%s:%d: bad JSON: %s" % (name, i, e))
                continue
            errs.extend(validate_record(rec, "%s:%d" % (name, i)))
            records.append(rec)
        errs.extend(_check_direction_consistency(records, name))
        return errs
    try:
        blob = json.loads(text)
    except ValueError as e:
        return ["%s: bad JSON: %s" % (name, e)]
    if _is_multichip(blob):
        errs = []
        for key, typ in (("n_devices", int), ("rc", int), ("ok", bool),
                         ("skipped", bool)):
            if not isinstance(blob.get(key), typ) or \
                    (typ is int and isinstance(blob.get(key), bool)):
                errs.append("%s: multichip field %r must be %s, got %r"
                            % (name, key, typ.__name__, blob.get(key)))
        return errs
    if _is_wrapper(blob):
        if not isinstance(blob.get("rc"), int):
            return ["%s: wrapper field 'rc' must be an int" % name]
        payload = blob.get("parsed")
        if payload is None:
            # a crashed driver run with no parsed line is a valid
            # *failure* artifact only when it says so
            return [] if blob["rc"] != 0 else \
                ["%s: rc=0 wrapper without a 'parsed' payload" % name]
    else:
        payload = blob
    errs = []
    if not isinstance(payload, dict):
        return ["%s: payload is not an object" % name]
    if not isinstance(payload.get("metric"), str):
        errs.append("%s: payload field 'metric' must be a string" % name)
    if not isinstance(payload.get("unit"), str):
        errs.append("%s: payload field 'unit' must be a string" % name)
    v = payload.get("value")
    if isinstance(v, bool) or not isinstance(v, (int, float)) or \
            not math.isfinite(v):
        errs.append("%s: payload field 'value' must be a finite number, "
                    "got %r" % (name, v))
    # every apply_breakdown / overlay_breakdown anywhere in the payload
    # (replay legs, replay_apply legs, scenario blocks, nested
    # last_device blocks) must schema-validate — breakdown sum
    # contracts are enforced in committed artifacts
    _walk_breakdowns(payload, name, errs)
    # every record the normalizer derives must itself validate
    for rec in records_from_bench(blob, name):
        errs.extend(validate_record(rec, name))
    return errs


def validate_parallel_close(pc, where: str = "") -> List[str]:
    """Schema check for a `parallel_close` block (ISSUE 13: the
    conflict-graph parallel-close gate leg): both apply walls must be
    finite positives and the recorded speedup must actually be their
    ratio — a speedup that drifts from its own numerator/denominator is
    a broken artifact, not a measurement."""
    errs: List[str] = []
    if not isinstance(pc, dict):
        return ["%s: parallel_close is not an object" % where]
    ser = _num(pc, "serial_apply_ms")
    par = _num(pc, "parallel_apply_ms")
    spd = _num(pc, "parallel_apply_speedup")
    for key, v in (("serial_apply_ms", ser), ("parallel_apply_ms", par),
                   ("parallel_apply_speedup", spd)):
        if v is None or v <= 0:
            errs.append("%s: parallel_close.%s must be a finite number "
                        "> 0, got %r" % (where, key, pc.get(key)))
    if not isinstance(pc.get("clusters"), int) or pc.get("clusters", 0) < 1:
        errs.append("%s: parallel_close.clusters must be a positive int"
                    % where)
    if not errs and abs(spd - ser / par) > max(0.01, 0.01 * spd):
        errs.append("%s: parallel_close.parallel_apply_speedup %.3f != "
                    "serial/parallel ratio %.3f" % (where, spd, ser / par))
    return errs


def _walk_breakdowns(blob, name: str, errs: List[str],
                     depth: int = 0) -> None:
    if depth > 6:
        return
    if isinstance(blob, list):
        for v in blob:
            _walk_breakdowns(v, name, errs, depth + 1)
        return
    if not isinstance(blob, dict):
        return
    if "apply_breakdown" in blob:
        errs.extend(validate_apply_breakdown(blob["apply_breakdown"], name))
    if "parallel_close" in blob:
        errs.extend(validate_parallel_close(blob["parallel_close"], name))
    if "overlay_breakdown" in blob:
        errs.extend(validate_overlay_breakdown(blob["overlay_breakdown"],
                                               name))
    if blob.get("propagation") is not None:
        ob = blob.get("overlay_breakdown")
        errs.extend(validate_propagation(
            blob["propagation"], name,
            flood=ob.get("flood") if isinstance(ob, dict) else None))
    if blob.get("ingress") is not None:
        errs.extend(validate_ingress(blob["ingress"], name))
    if blob.get("scp") is not None:
        errs.extend(validate_scp(blob["scp"], name))
    if blob.get("footprint") is not None:
        errs.extend(validate_footprint(blob["footprint"], name))
    if "fleet_verify" in blob:
        errs.extend(validate_fleet_verify(blob["fleet_verify"], name))
    if "hash_bench" in blob:
        errs.extend(validate_hash_bench(blob["hash_bench"], name))
    if "bucketdb_bench" in blob:
        errs.extend(validate_bucketdb(blob["bucketdb_bench"], name))
    for v in blob.values():
        if isinstance(v, (dict, list)):
            _walk_breakdowns(v, name, errs, depth + 1)


def _check_direction_consistency(records, name: str) -> List[str]:
    seen: Dict[str, str] = {}
    errs = []
    for rec in records:
        if not isinstance(rec, dict):
            continue
        m, d = rec.get("metric"), rec.get("direction")
        if not isinstance(m, str) or d not in DIRECTIONS:
            continue
        if m in seen and seen[m] != d:
            errs.append("%s: metric %r has conflicting directions %s/%s"
                        % (name, m, seen[m], d))
        seen.setdefault(m, d)
    return errs


# --------------------------------------------------------------------------
# history + comparison

def load_history(path: str) -> List[dict]:
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            if line.strip():
                out.append(json.loads(line))
    return out


def best_baselines(history) -> Dict[Tuple[str, str], dict]:
    """Best committed record per (metric, platform), direction-aware."""
    best: Dict[Tuple[str, str], dict] = {}
    for rec in history:
        errs = validate_record(rec, "history")
        if errs:
            continue
        key = (rec["metric"], rec["platform"])
        cur = best.get(key)
        if cur is None:
            best[key] = rec
        elif rec["direction"] == "higher" and rec["value"] > cur["value"]:
            best[key] = rec
        elif rec["direction"] == "lower" and rec["value"] < cur["value"]:
            best[key] = rec
    return best


def compare(current, history, tolerance: float = 0.1) -> dict:
    """Diff `current` records against the best committed baseline per
    (metric, platform). A record regresses when it is worse than the
    best baseline by more than `tolerance` (fractional); records with
    no baseline land in `new` and never gate."""
    base = best_baselines(history)
    report = {"tolerance": tolerance, "regressions": [],
              "improvements": [], "ok": [], "new": []}
    for c in current:
        errs = validate_record(c, "current")
        if errs:
            report["regressions"].append(
                {"metric": c.get("metric"), "error": "; ".join(errs)})
            continue
        key = (c["metric"], c["platform"])
        b = base.get(key)
        if b is None:
            report["new"].append({"metric": c["metric"],
                                  "platform": c["platform"],
                                  "value": c["value"]})
            continue
        entry = {"metric": c["metric"], "platform": c["platform"],
                 "current": c["value"], "best": b["value"],
                 "best_source": b.get("source"),
                 "direction": c["direction"]}
        if b["value"]:
            delta = (c["value"] - b["value"]) / abs(b["value"])
            entry["delta_pct"] = round(100.0 * delta, 2)
        if c["direction"] == "higher":
            regressed = c["value"] < b["value"] * (1.0 - tolerance)
            improved = c["value"] > b["value"]
        else:
            regressed = c["value"] > b["value"] * (1.0 + tolerance)
            improved = c["value"] < b["value"]
        (report["regressions"] if regressed else
         report["improvements"] if improved else
         report["ok"]).append(entry)
    return report


def append_history(path: str, records) -> int:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    n = 0
    with open(path, "a", encoding="utf-8") as fh:
        for rec in records:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
            n += 1
    return n


# --------------------------------------------------------------------------
# ingest

def default_artifacts(root: str = REPO) -> List[str]:
    return sorted(glob.glob(os.path.join(root, "BENCH_*.json")) +
                  glob.glob(os.path.join(root, "MULTICHIP_*.json")))


def ingest(paths, out_path: Optional[str] = None) -> List[dict]:
    """Normalize every artifact into records, deduplicated (cached
    last_device blocks repeat verbatim across rounds) and
    deterministically ordered; optionally write them as JSONL."""
    records: List[dict] = []
    seen = set()
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            blob = json.load(fh)
        for rec in normalize_any(blob, os.path.basename(path)):
            key = (rec["metric"], rec["platform"], rec["value"],
                   rec.get("at_unix"))
            if key in seen:
                continue
            seen.add(key)
            records.append(rec)
    records.sort(key=lambda r: (r.get("round") if r.get("round")
                                is not None else -1,
                                r["source"], r["metric"], r["platform"]))
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as fh:
            for rec in records:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return records


# --------------------------------------------------------------------------
# CLI

def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # `--check` alias: the tier-1 invocation in ISSUE 6 reads
    # `tools/bench_compare.py --check`
    if argv and argv[0] == "--check":
        argv[0] = "check"
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_in = sub.add_parser("ingest", help="normalize artifacts to JSONL")
    p_in.add_argument("files", nargs="*")
    p_in.add_argument("--out", default=os.path.join(REPO, DEFAULT_HISTORY))
    p_ck = sub.add_parser("check", help="validate artifact schemas")
    p_ck.add_argument("files", nargs="*")
    p_cp = sub.add_parser("compare", help="gate a run against history")
    p_cp.add_argument("--current", required=True)
    p_cp.add_argument("--history",
                      default=os.path.join(REPO, DEFAULT_HISTORY))
    p_cp.add_argument("--tolerance", type=float, default=0.1)
    args = ap.parse_args(argv)

    if args.cmd == "ingest":
        paths = args.files or default_artifacts()
        records = ingest(paths, args.out)
        print("ingested %d records from %d artifacts -> %s"
              % (len(records), len(paths), args.out))
        return 0

    if args.cmd == "check":
        paths = args.files or default_artifacts()
        hist = os.path.join(REPO, DEFAULT_HISTORY)
        if not args.files and os.path.exists(hist):
            paths = paths + [hist]
        errors: List[str] = []
        for p in paths:
            errors.extend(check_artifact(p))
        for e in errors:
            print("MALFORMED %s" % e)
        print("%s: %d artifacts checked, %d errors"
              % ("FAIL" if errors else "OK", len(paths), len(errors)))
        return 1 if errors else 0

    if args.cmd == "compare":
        with open(args.current, encoding="utf-8") as fh:
            blob = json.load(fh)
        current = normalize_any(blob, os.path.basename(args.current))
        history = load_history(args.history)
        report = compare(current, history, tolerance=args.tolerance)
        print(json.dumps(report, indent=1, sort_keys=True))
        return 1 if report["regressions"] else 0

    return 2


if __name__ == "__main__":
    sys.exit(main())
