"""Benchmark: batched ed25519 verify throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline = measured device rate / single-core CPU (OpenSSL) rate — the
reference's implicit baseline is single-call libsodium verify
(BASELINE.md; reference crypto bench harness src/crypto/test/
CryptoTests.cpp:235-258). The north-star target is >=100K verifies/s/chip.

Robustness contract (round-1 postmortem): the ambient axon/TPU-relay env
can hang or fail JAX init, so the orchestrator process NEVER imports jax.
It runs the device bench in a child process with a hard timeout, and on
failure falls back to (1) a scrubbed virtual-CPU jax run, then (2) the
framework's synchronous OpenSSL backend — so `value` is always > 0 and
the real failure text is recorded in the JSON.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))


# --- CPU baseline (no jax) -------------------------------------------------

def _example_batch(batch: int, n_keys: int = 32):
    """Deterministic signed batch without importing jax (mirrors
    models/verifier_model.make_example_batch, which pulls in jnp)."""
    from stellar_core_tpu.crypto.keys import SecretKey
    sks = [SecretKey.from_seed(bytes([i + 1] * 32)) for i in range(n_keys)]
    pubs, sigs, msgs = [], [], []
    for i in range(batch):
        sk = sks[i % n_keys]
        m = b"bench-msg-%08d" % i
        pubs.append(sk.public_key.key_bytes)
        sigs.append(sk.sign(m))
        msgs.append(m)
    return pubs, sigs, msgs


def cpu_baseline_rate(n: int = 2000) -> float:
    from stellar_core_tpu.crypto.keys import raw_verify
    pubs, sigs, msgs = _example_batch(n)
    t0 = time.perf_counter()
    ok = True
    for p, s, m in zip(pubs, sigs, msgs):
        ok &= raw_verify(p, s, m)
    dt = time.perf_counter() - t0
    assert ok
    return n / dt


# --- device bench (child process) ------------------------------------------

def _prep_args(batch: int, n_keys: int = 64) -> tuple:
    """Signed batch → device-ready jnp arg tuple for verify_batch_jit."""
    import jax.numpy as jnp
    from stellar_core_tpu.ops import ed25519 as E
    pubs, sigs, msgs = _example_batch(batch, n_keys=n_keys)
    prep = E.prepare_batch(pubs, sigs, msgs)
    return tuple(jnp.asarray(prep[k]) for k in
                 ("ay", "a_sign", "ry", "r_sign", "s_nibs", "k_nibs"))


def device_bench(batch: int = 8192, iters: int = 10,
                 args: tuple | None = None) -> dict:
    """Runs in the child: jax on whatever platform the env provides."""
    t_init = time.perf_counter()
    import jax
    platform = jax.devices()[0].platform
    init_s = time.perf_counter() - t_init

    from stellar_core_tpu.ops import ed25519 as E
    if args is None:
        args = _prep_args(batch)
    t_c = time.perf_counter()
    ok = E.verify_batch_jit(*args)
    ok.block_until_ready()
    compile_s = time.perf_counter() - t_c
    assert bool(ok.all()), "verify kernel rejected valid signatures"
    best = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        E.verify_batch_jit(*args).block_until_ready()
        dt = time.perf_counter() - t0
        best = max(best, batch / dt)
    out = {"rate": best, "platform": platform, "batch": batch,
           "init_s": round(init_s, 2), "compile_s": round(compile_s, 2)}
    # live-SCP SLO: per-dispatch latency of the SMALL (128) bucket — the
    # p50/p99 consensus actually feels (SCP timers budget ~1s)
    try:
        args2 = _prep_args(128, n_keys=32)
        E.verify_batch_jit(*args2).block_until_ready()   # compile shape
        lats = []
        for _ in range(50):
            t0 = time.perf_counter()
            E.verify_batch_jit(*args2).block_until_ready()
            lats.append(time.perf_counter() - t0)
        lats.sort()
        out["latency128_p50_ms"] = round(lats[len(lats) // 2] * 1000, 3)
        out["latency128_p99_ms"] = round(lats[-1] * 1000, 3)
    except Exception as e:   # noqa: BLE001 - recorded, not swallowed
        out["latency128_error"] = repr(e)[:200]
    return out


def device_full_bench(partial_path: str, batch: int = 8192,
                      iters: int = 10) -> dict:
    """ALL device legs in ONE child process (round-4 postmortem: a second
    device process is a second chance to wedge the single-tenant relay),
    written to `partial_path` INCREMENTALLY after each stage — a wedge in
    stage N still leaves stages 1..N-1 on disk for the orchestrator.

    Stages: init → kernel throughput (8192) + 128-latency SLO →
    warm-recompile via the persistent XLA cache → catchup-replay (tpu
    backend leg of north star #2).
    """
    results: dict = {}

    def flush(stage: str) -> None:
        results["last_stage_done"] = stage
        tmp = partial_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(results, fh)
        os.replace(tmp, partial_path)

    # stage 0: jax init (timed here; _prep_args below already touches the
    # device via jnp.asarray, so device_bench's own init timer would read 0)
    t_init = time.perf_counter()
    import jax as _jax
    results["platform"] = _jax.devices()[0].platform
    init_s = round(time.perf_counter() - t_init, 2)
    results["init_s"] = init_s
    flush("init")

    # stage 1: kernel throughput + latency
    args = _prep_args(batch)
    res = device_bench(batch=batch, iters=iters, args=args)
    res["init_s"] = init_s
    results.update(res)
    flush("kernel")

    # stage 2: warm compile. clear_caches drops the in-memory executable
    # but keeps the persistent on-disk cache (JAX_COMPILATION_CACHE_DIR),
    # so this re-jit measures the WARM-restart compile the README claims.
    # (`compile_s` above is the cold number only when .jax_cache had no
    # entry for this kernel/platform; `compile_warm_s` is always warm.)
    import jax
    from stellar_core_tpu.ops import ed25519 as E
    jax.clear_caches()
    t_w = time.perf_counter()
    E.verify_batch_jit(*args).block_until_ready()
    results["compile_warm_s"] = round(time.perf_counter() - t_w, 2)
    flush("warm_compile")

    # stage 2b: cockpit warmup — the buckets this run MEASURED, chosen
    # through the histogram-driven selection (ISSUE 11): the traffic
    # stages 1 dispatched (throughput batch + the 128-latency SLO leg)
    # is recorded into a VerifierStats, warmup_plan derives the adaptive
    # set from it, and the plan is persisted beside the XLA cache — so
    # `warmup_buckets_s` reflects the adaptive set and a warm restart on
    # this host compiles only the buckets real traffic used.
    try:
        from stellar_core_tpu.crypto.batch_verifier import (
            TpuSigVerifier, VerifierStats, warmup_plan)
        v = TpuSigVerifier()
        v.BUCKETS = (128, batch)   # instance override; class attr untouched
        v.stats = VerifierStats()
        # replay this run's observed batch mix through the cockpit
        for _ in range(iters):
            v.stats.record_bucket_dispatch(batch, batch, 0)
        if "latency128_p50_ms" in results:
            for _ in range(50):
                v.stats.record_bucket_dispatch(128, 128, 0)
        plan, plan_info = warmup_plan(v.stats, v.BUCKETS)
        results["warmup_plan"] = {"buckets": plan, **plan_info}
        results["warmup_plan_path"] = v.save_warmup_plan()
        jax.clear_caches()     # a fresh process's in-memory state
        v.warmup(wait=True)
        w = v.stats.warmup
        results["warmup_state"] = w["state"]
        results["warmup_source"] = w["source"]
        results["warmup_buckets_s"] = {
            b: info["seconds"] for b, info in w["buckets"].items()}
        results["compile_cache"] = dict(v.stats.compile_cache)
    except Exception as e:   # noqa: BLE001 - recorded, not swallowed
        results["warmup_error"] = repr(e)[:200]
    flush("cockpit_warmup")

    # stage 3: replay, tpu backend (cpu leg runs in a scrubbed child so
    # the ratio's denominator never touches the relay). The stage flushes
    # at each internal phase (publish, each replay attempt) so the
    # orchestrator's stall watchdog sees progress — a mid-stage kill of a
    # live JAX client is what wedges the relay (r5 postmortem: the
    # publish+warmup+2-replay stage overran the old single-flush window).
    try:
        results["replay_tpu"] = replay_bench(
            "tpu", progress=lambda ph: flush("replay_tpu:" + ph))
    except Exception as e:   # noqa: BLE001 - recorded, not swallowed
        results["replay_tpu_error"] = repr(e)[:400]
    flush("replay_tpu")
    return results




class _StandardMix:
    """Mixed-op traffic for the standard replay mix (ISSUE 13): every
    4th dense ledger carries change-trust / allow-trust / offers / path
    payments / manage-data / bump-sequence / account-merge / inflation /
    fee-bump / muxed ops from dedicated role accounts, so the replay
    exercises (and the zero-bail gate covers) every wire op type."""

    def __init__(self, app, adapter, root, roles) -> None:
        self.app = app
        self.adapter = adapter
        self.root = root
        self.roles = roles
        self.issuer = roles[0]
        self.merge_n = 0

    def setup(self) -> None:
        from stellar_core_tpu.xdr import AccountFlags, Asset
        app, issuer = self.app, self.issuer
        app.submit_transaction(issuer.tx([issuer.op_set_options(
            set_flags=AccountFlags.AUTH_REQUIRED_FLAG |
            AccountFlags.AUTH_REVOCABLE_FLAG)]))
        app.manual_close()
        self.USD = Asset.credit("USD", issuer.account_id)
        lines = self.roles[1:9]
        for r in lines:
            app.submit_transaction(
                r.tx([r.op_change_trust(self.USD, 10 ** 12)]))
        app.manual_close()
        app.submit_transaction(issuer.tx(
            [issuer.op_allow_trust(r.account_id, b"USD\x00")
             for r in lines]))
        app.manual_close()
        app.submit_transaction(issuer.tx(
            [issuer.op_payment(r.account_id, 10 ** 9, self.USD)
             for r in lines[:4]]))
        app.manual_close()

    def submit_mixed_ops(self, rnd: int) -> None:
        from stellar_core_tpu.crypto.keys import SecretKey
        from stellar_core_tpu.testing import TestAccount
        from stellar_core_tpu.transactions.transaction_frame import (
            FeeBumpTransactionFrame,
        )
        from stellar_core_tpu.xdr import (
            Asset, EnvelopeType, FeeBumpTransaction,
            FeeBumpTransactionEnvelope, MuxedAccount, OperationBody,
            OperationType, PaymentOp, TransactionEnvelope, _Ext,
        )
        from stellar_core_tpu.xdr.basic import MuxedAccountMed25519
        from stellar_core_tpu.xdr.transaction import (
            BumpSequenceOp, PathPaymentStrictReceiveOp,
            PathPaymentStrictSendOp, _InnerTxEnvelope,
        )
        app, USD = self.app, self.USD
        r = self.roles
        sub = app.submit_transaction
        native = Asset.native()
        # trust-line churn + data + bump-sequence
        sub(r[9].tx([r[9].op_change_trust(USD, 10 ** 10 + rnd),
                     r[9].op_manage_data("bench-k", b"v%d" % rnd)]))
        sub(r[10].tx([r[10].op_manage_data("tmp%d" % (rnd % 3),
                                           b"x" if rnd % 2 else None)]))
        sub(r[11].tx([r[11].op(OperationBody(
            OperationType.BUMP_SEQUENCE,
            BumpSequenceOp(bumpTo=r[11].next_seq() + 3)))]))
        # order book: r[1] posts USD/native, r[2] crosses with a buy,
        # r[3] sends a strict-receive path payment through the book
        sub(r[1].tx([r[1].op_manage_sell_offer(USD, native, 500 + rnd,
                                               2, 1)]))
        sub(r[2].tx([r[2].op_manage_buy_offer(native, USD, 60 + rnd,
                                              1, 2)]))
        sub(r[3].tx([r[3].op(OperationBody(
            OperationType.PATH_PAYMENT_STRICT_RECEIVE,
            PathPaymentStrictReceiveOp(
                sendAsset=USD, sendMax=10 ** 8,
                destination=r[4].muxed, destAsset=native,
                destAmount=40 + rnd, path=[])))]))
        sub(r[4].tx([r[4].op(OperationBody(
            OperationType.PATH_PAYMENT_STRICT_SEND,
            PathPaymentStrictSendOp(
                sendAsset=USD, sendAmount=25 + rnd,
                destination=r[5].muxed, destAsset=native,
                destMin=1, path=[])))]))
        # allow-trust flap on a line with no open offers
        sub(self.issuer.tx([self.issuer.op_allow_trust(
            r[6].account_id, b"USD\x00",
            authorize=2 if rnd % 2 else 1)]))
        # account merge: fund a throwaway, merge it back next round
        if self.merge_n:
            prev = TestAccount(self.adapter, SecretKey.from_seed(
                bytes([93, self.merge_n & 0xFF] + [5] * 30)))
            sub(prev.tx([prev.op(OperationBody(
                OperationType.ACCOUNT_MERGE,
                MuxedAccount.from_account_id(self.root.account_id)))]))
        self.merge_n += 1
        fodder = SecretKey.from_seed(
            bytes([93, self.merge_n & 0xFF] + [5] * 30))
        sub(r[12].tx([r[12].op_create_account(fodder.public_key,
                                              3 * 10 ** 7)]))
        # (no INFLATION tx: at protocol 13 the op is version-retired, so
        # the queue rejects it at admission — it can never reach a
        # txset; the differential oracle covers its native
        # opNOT_SUPPORTED arm instead)
        # fee bump: r[14] sponsors a payment from r[15]
        inner = r[15].tx([r[15].op_payment(self.root.account_id, 5)])
        fb = FeeBumpTransaction(
            feeSource=r[14].muxed, fee=2000,
            innerTx=_InnerTxEnvelope(EnvelopeType.ENVELOPE_TYPE_TX,
                                     inner.envelope.value),
            ext=_Ext.v0())
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
            FeeBumpTransactionEnvelope(tx=fb, signatures=[]))
        frame = FeeBumpTransactionFrame(app.config.network_id, env)
        frame.add_signature(r[14].sk)
        sub(frame)
        # muxed destination payment
        sub(r[16].tx([r[16].op(OperationBody(
            OperationType.PAYMENT,
            PaymentOp(
                destination=MuxedAccount(
                    0x100, MuxedAccountMed25519(
                        id=7, ed25519=r[17].account_id.key_bytes)),
                asset=native, amount=9 + rnd)))]))


def replay_bench(backend: str, n_checkpoints: int = 4,
                 txs_per_ledger: int = 100, sigs_per_tx: int = 20,
                 progress=None, repeats: int | None = None,
                 mix: str = "multisig") -> dict:
    """Catchup-replay benchmark: the second north-star metric
    (BASELINE.md: >=5x pubnet replay vs libsodium CPU; reference
    methodology /root/reference/performance-eval/performance-eval.md:52-66).

    Publishes a dense synthetic history to a tmpdir file archive, then
    times a fresh node replaying it with the given SIG_VERIFY_BACKEND.
    Runs in a child process.

    mix="multisig" (legacy, history-comparable): every tx a
    sigs_per_tx-of-N multisig payment to one hub account — the shape
    where signature checking dominates checkValid.
    mix="standard" (ISSUE 13): the full-coverage traffic mix — 2-sig
    senders paying DISJOINT partner accounts (conflict-light: the
    parallel close engages), with every 4th ledger carrying the other
    op types (trust lines, allow-trust, offers, path payments, account
    data, bump-sequence, merges, inflation, fee bumps, muxed
    destinations). The replay must drive ledger.apply.native-bail.* to
    zero on this mix (asserted by `bench.py --replay-full`)."""
    import shutil
    import tempfile

    from stellar_core_tpu.crypto import keys as _keys
    from stellar_core_tpu.catchup.catchup_work import CatchupConfiguration
    from stellar_core_tpu.history.archive import HistoryArchive
    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.config import Config
    from stellar_core_tpu.testing import AppLedgerAdapter
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    from stellar_core_tpu.work.basic_work import State

    freq = 8
    # One bucket shape for the whole replay, AOT-compiled off the clock in
    # app.start()'s warmup + the explicit warmup(wait=True) below (the
    # r4->r5 0.026x pathology: BUCKETS=(1024,) was never AOT-compiled, and
    # the default warmup raced three other shapes onto the device during
    # the timed window). 8192 is the shape the throughput leg compiles in
    # stage 1 — in-memory hit in the same process, persistent-cache hit in
    # a fresh one. (A 16384 experiment measured NO gain — the drain is not
    # RTT-bound at this scale — and its one-off cold compile overran the
    # stall watchdog, which kills the child and wedges the relay.)
    from stellar_core_tpu.crypto.batch_verifier import TpuSigVerifier
    old_buckets = TpuSigVerifier.BUCKETS   # restored below: the tiny
    # --compare leg runs this function IN-PROCESS (tier-1 test), where a
    # leaked class-attr override would bleed into later tests
    TpuSigVerifier.BUCKETS = (8192,)
    tmp = tempfile.mkdtemp(prefix="sct-replay-")
    try:
        archive_root = os.path.join(tmp, "archive")
        os.makedirs(archive_root, exist_ok=True)

        def make_app(n, writable, be):
            cfg = Config.test_config(n)
            cfg.DATABASE = "sqlite3://:memory:"
            cfg.CHECKPOINT_FREQUENCY = freq
            cfg.SIG_VERIFY_BACKEND = be
            # production perf config, identical for both legs: reference
            # pubnet validators run with no invariants unless configured
            # (Config.h INVARIANT_CHECKS default empty), and the genesis
            # op capacity must admit the 20-op multisig-arming txs
            # (maxTxSetSize counts OPS from protocol 11)
            cfg.INVARIANT_CHECKS = []
            cfg.TESTING_UPGRADE_MAX_TX_SET_SIZE = 10_000
            arch = HistoryArchive.local_dir("bench", archive_root)
            d = {"get": arch.get_tmpl, "mkdir": arch.mkdir_tmpl}
            if writable:
                d["put"] = arch.put_tmpl
            cfg.HISTORY = {"bench": d}
            app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
            app.enable_buckets(os.path.join(tmp, "buckets-%d" % n))
            app.start()
            return app

        # --- publish a dense history (cpu backend; cost excluded) ---------
        pub = make_app(0, True, "cpu")
        adapter = AppLedgerAdapter(pub)
        root = adapter.root_account()
        # one 100-op tx creates every sender in a single close (per-sender
        # create() closes would advance closeTime past the 60s drift guard)
        from stellar_core_tpu.crypto.keys import SecretKey
        from stellar_core_tpu.testing import TestAccount
        if mix == "standard":
            sigs_per_tx = 2     # pubnet-realistic signature density
        n_roles = 20 if mix == "standard" else 0
        sender_sks = [SecretKey.from_seed(bytes([7, i & 0xFF] + [11] * 30))
                      for i in range(txs_per_ledger + n_roles)]
        for lo in range(0, len(sender_sks), 100):
            pub.submit_transaction(root.tx(
                [root.op_create_account(sk.public_key, 10**10)
                 for sk in sender_sks[lo:lo + 100]]))
            pub.manual_close()
        senders = [TestAccount(adapter, sk) for sk in sender_sks]
        roles = senders[txs_per_ledger:]
        senders = senders[:txs_per_ledger]
        extra_signers = {}
        if sigs_per_tx > 1:
            for i, s in enumerate(senders):
                ks = [SecretKey.from_seed(bytes([201 + j, i & 0xFF] + [7] * 30))
                      for j in range(sigs_per_tx - 1)]
                ops = [s.op_add_signer(k.public_key.key_bytes) for k in ks]
                ops.append(s.op_set_options(med=sigs_per_tx))
                pub.submit_transaction(s.tx(ops))
                extra_signers[i] = ks
            pub.manual_close()   # one ledger arms every sender's multisig
        mixer = _StandardMix(pub, adapter, root, roles) \
            if mix == "standard" else None
        if mixer is not None:
            mixer.setup()
        # keep virtual time ahead of ledger closeTime (it advances 1s per
        # close; the herder rejects values >60s ahead of the local clock —
        # reference MAXIMUM_LEDGER_CLOSETIME_DRIFT behavior)
        pub.clock.set_virtual_time(
            pub.clock.now() + pub.ledger_manager.last_closed_ledger_num())
        start = pub.ledger_manager.last_closed_ledger_num()
        target_cps = pub.history_manager.published_checkpoints + \
            n_checkpoints
        dense = 0
        while pub.history_manager.published_checkpoints < target_cps:
            if mixer is not None:
                # conflict-light pairs: sender 2k pays sender 2k+1 and
                # vice versa — 50 disjoint clusters per close, so the
                # conflict-graph parallel close engages on replay
                for i, snd in enumerate(senders):
                    partner = senders[i + 1 if i % 2 == 0 else i - 1]
                    pub.submit_transaction(
                        snd.tx([snd.op_payment(partner.account_id, 1000)],
                               extra_signers=extra_signers.get(i)))
                if dense % 4 == 1:
                    mixer.submit_mixed_ops(dense)
            else:
                for i, snd in enumerate(senders):
                    pub.submit_transaction(
                        snd.tx([snd.op_payment(root.account_id, 1000)],
                               extra_signers=extra_signers.get(i)))
            pub.clock.set_virtual_time(pub.clock.now() + 1.0)
            pub.manual_close()
            dense += 1
            # drain queued publish work before closing more (the loop is
            # bounded by published checkpoints, not closes)
            pub.crank_until(
                lambda: pub.history_manager.publish_queue() == [],
                max_cranks=20000)
        # archive tip = newest checkpoint boundary at-or-below the LCL
        # (the queue is drained, so every checkpoint <= lcl is published)
        lcl = pub.ledger_manager.last_closed_ledger_num()
        tip = ((lcl + 1) // freq) * freq - 1
        dense_past_tip = max(0, lcl - tip)
        if progress is not None:
            progress("publish")

        # --- replay it with the target backend. Best-of-`repeats` over
        # the SAME published history: ambient relay latency varies run to
        # run by several hundred ms per drain, so a single replay is a
        # noisy sample; each attempt gets a fresh node + cleared caches.
        def one_replay() -> dict:
            with _keys._cache_lock:
                _keys._verify_cache.clear()  # earlier runs filled it
            app = make_app(1, False, backend)
            # span tracer on for the whole replay: BENCH artifacts carry
            # a machine-generated phase_breakdown instead of a prose
            # Amdahl estimate (ISSUE 2; docs/observability.md). Capacity
            # sized so no replay span is ever evicted (~110 spans/ledger).
            app.tracer.enable(capacity=65536)
            # account time spent inside the verifier's batch drain: the
            # crypto-subsystem speedup (whole-checkpoint batch path)
            # reported alongside the end-to-end ratio
            crypto = {"s": 0.0, "sigs": 0}
            _orig_pw = app.sig_verifier.prewarm_many
            _orig_vm = app.sig_verifier.verify_many

            def timed_prewarm(triples):
                t = time.perf_counter()
                out = _orig_pw(triples)
                crypto["s"] += time.perf_counter() - t
                return out

            def counted_verify_many(triples):
                # only cache MISSES reach verify_many — this is the
                # actual device/CPU crypto work
                crypto["sigs"] += len(triples)
                return _orig_vm(triples)

            app.sig_verifier.prewarm_many = timed_prewarm
            app.sig_verifier.verify_many = counted_verify_many
            app.clock.set_virtual_time(pub.clock.now() + 10.0)
            v = getattr(app, "sig_verifier", None)
            if v is not None and hasattr(v, "warmup"):
                v.warmup(wait=True)       # compile off the clock
            work = app.catchup_manager.start_catchup(
                CatchupConfiguration.complete())
            t0 = time.perf_counter()
            for _ in range(10**7):
                if work.is_done():
                    break
                app.crank(False)
            wall = time.perf_counter() - t0
            assert work.state == State.SUCCESS, "catchup replay failed"
            got = app.ledger_manager.last_closed_ledger_num()
            assert got == tip, (got, tip)
            n_ledgers = got - 1   # replayed from genesis
            # only dense closes inside the replayed range count
            n_txs = (dense - dense_past_tip) * txs_per_ledger
            # span-derived phase attribution: exclusive per-phase totals
            # (+ untraced remainder) sum to the measured wall; verify
            # drains key by configured backend AND actual platform, so a
            # fallback leg can never masquerade as device time
            phase_breakdown = app.tracer.phase_breakdown(wall_s=wall)
            # close-cockpit apply attribution (ISSUE 9): per-op ms +
            # bail reasons + state-read stats; per_op_ms + other_ms sum
            # to apply_wall_s by construction (ledger/apply_stats.py)
            apply_breakdown = \
                app.ledger_manager.apply_stats.apply_breakdown()
            stats = app.ledger_manager.apply_stats
            return {"backend": backend, "mix": mix,
                    "native_bails": dict(stats.bails),
                    "python_closes": stats.closes.get("python", 0),
                    "clusters": dict(stats.clusters),
                    "ledgers": n_ledgers,
                    "dense_ledgers": dense, "wall_s": round(wall, 3),
                    "ledgers_per_sec": round(n_ledgers / wall, 2),
                    "txs_per_sec": round(n_txs / wall, 1),
                    "txs_per_ledger": txs_per_ledger,
                    "sigs_per_tx": sigs_per_tx,
                    "crypto_s": round(crypto["s"], 3),
                    "crypto_sigs": crypto["sigs"],
                    "phase_breakdown": phase_breakdown,
                    "apply_breakdown": apply_breakdown}

        if repeats is None:
            repeats = int(os.environ.get("BENCH_REPLAY_REPEATS", "2"))
        best = None
        for k in range(max(1, repeats)):
            r = one_replay()
            if progress is not None:
                progress("replay%d" % (k + 1))
            if best is None or r["wall_s"] < best["wall_s"]:
                best = r
        return best
    finally:
        TpuSigVerifier.BUCKETS = old_buckets
        shutil.rmtree(tmp, ignore_errors=True)


def chaos_smoke(n_ledgers: int = 30, txs_per_ledger: int = 10) -> dict:
    """`bench.py --chaos`: close-latency p95 with the fault schedule on
    vs off (ISSUE 3; docs/robustness.md). Both legs run the same seeded
    standalone load through the cpu-resilient backend; the chaos leg
    injects device-dispatch failures at p=0.2, so drains pay the
    failed-dispatch-plus-fallback cost and the breaker occasionally
    trips. Pure-Python (no jax import): safe to run inline."""
    from stellar_core_tpu.crypto import keys as _keys
    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.config import Config
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.util import rnd
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    def one_leg(faults_on: bool) -> dict:
        rnd.reseed(0xC4A05)
        _keys.flush_verify_cache()
        cfg = Config.test_config(60, backend="cpu-resilient")
        cfg.SIG_VERIFY_BREAKER_COOLDOWN = 0.5
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
        app.start()
        if faults_on:
            app.faults.configure("device.dispatch", probability=0.2)
        lg = LoadGenerator(app)
        lg.generate_accounts(20)
        app.manual_close()
        for _ in range(n_ledgers):
            lg.generate_payments(txs_per_ledger)
            # cold verify cache per close: every drain actually dispatches
            _keys.flush_verify_cache()
            app.clock.set_virtual_time(app.clock.now() + 1.0)
            app.manual_close()
        t = app.metrics.new_timer("ledger.ledger.close")
        m = app.metrics.to_json()
        return {
            "close_p95_ms": round(t.percentile(0.95) * 1e3, 3),
            "close_mean_ms": round(t.mean() * 1e3, 3),
            "ledgers": n_ledgers,
            "breaker_trips": app.sig_verifier.breaker.trips,
            "fallback_drains": m.get("crypto.verify.fallback-drain",
                                     {}).get("count", 0),
            "injected": m.get("fault.injected.device.dispatch",
                              {}).get("count", 0),
        }

    off = one_leg(False)
    on = one_leg(True)
    out = {"metric": "chaos_close_latency_p95", "unit": "ms",
           "faults_off": off, "faults_on": on}
    if off["close_p95_ms"] > 0:
        out["p95_ratio_on_vs_off"] = round(
            on["close_p95_ms"] / off["close_p95_ms"], 3)
    return out


def fleet_bench(n_nodes: int = 3, n_ledgers: int = 12) -> dict:
    """`bench.py --fleet`: the multi-node leg (ISSUE 4;
    docs/observability.md#fleet-view). Runs an n-node simulation over
    the REAL overlay stack (Peer handshake/HMAC/flood — the wire
    cockpit needs actual frames to account, ISSUE 10) with per-node
    tracing on, closes >= n_ledgers ledgers under a light payment load,
    and reports the fleet aggregate — slot-latency p50/p95, externalize
    skew, per-slot bandwidth totals, flood duplication ratio and
    tx-latency p50/p95 — from the merged slot timelines + overlay
    exports. Pure Python (no jax import): safe to run inline."""
    from stellar_core_tpu.simulation import topologies
    from stellar_core_tpu.simulation.simulation import Simulation
    from stellar_core_tpu.testing import AppLedgerAdapter
    from stellar_core_tpu.util import rnd

    rnd.reseed(0xF1EE7)
    sim = topologies.core(
        n_nodes, max(2, (n_nodes * 2 + 1) // 3),
        mode=Simulation.OVER_PEERS,
        cfg_tweak=lambda c: (setattr(c, "TRACE_ENABLED", True),
                             setattr(c, "DATABASE", "sqlite3://:memory:")))
    sim.start_all_nodes()
    first = next(iter(sim.nodes.values())).app
    sim.crank_until(lambda: sim.have_all_externalized(2), 60000)
    # payment load through the real overlay: the tx-lifecycle funnel
    # measures submit→applied end to end
    ad = AppLedgerAdapter(first)
    root = ad.root_account()
    base_seq = ad.seq_num(root.account_id)
    for i in range(4):
        first.submit_transaction(root.tx(
            [root.op_payment(root.account_id, 1 + i)],
            seq=base_seq + 1 + i))
    target = 1 + n_ledgers   # genesis is seq 1; n_ledgers consensus closes
    ok = sim.crank_until(lambda: sim.have_all_externalized(target),
                         200000)
    agg = sim.fleet()     # one aggregation feeds both views
    stats = agg.fleet_stats()
    trace = agg.merged_chrome_trace()
    overlay = agg.overlay_breakdown()
    summary = stats["summary"]
    out = {
        "metric": "fleet_slot_latency",
        "unit": "ms",
        # stable gating key for records derived from this payload (the
        # overlay_breakdown normalizer keys per metric+platform)
        "platform": "fleet-sim",
        "nodes": n_nodes,
        "ledgers_closed": min(
            n.app.ledger_manager.last_closed_ledger_num()
            for n in sim.nodes.values()) - 1,
        "converged": bool(ok),
        "fleet": {
            "slot_count": summary["slot_count"],
            "slot_latency_p50_ms": round(
                summary["slot_latency_p50_s"] * 1e3, 3),
            "slot_latency_p95_ms": round(
                summary["slot_latency_p95_s"] * 1e3, 3),
            "externalize_skew_p50_ms": round(
                summary["externalize_skew_p50_s"] * 1e3, 3),
            "externalize_skew_max_ms": round(
                summary["externalize_skew_max_s"] * 1e3, 3),
            "stragglers": summary["stragglers"],
            "trace_events": len(trace["traceEvents"]),
            "dropped_spans": trace["dropped_spans"],
        },
    }
    # wire cockpit (ISSUE 10): fleet bandwidth totals + tx-latency
    # percentiles ride in the fleet block, the full overlay_breakdown
    # is schema-validated by tools/bench_compare.py
    if overlay is not None:
        out["overlay_breakdown"] = overlay
        out["fleet"]["recv_bytes_total"] = overlay["recv_bytes"]
        out["fleet"]["send_bytes_total"] = overlay["send_bytes"]
        out["fleet"]["flood_duplication_ratio"] = \
            overlay["flood"]["duplication_ratio"]
        out["fleet"]["tx_latency_p50_ms"] = \
            overlay["tx_latency_ms"]["p50"]
        out["fleet"]["tx_latency_p95_ms"] = \
            overlay["tx_latency_ms"]["p95"]
    # propagation cockpit (ISSUE 17): relay-tree percentiles + the
    # redundant bandwidth share that must reconcile with the flood
    # duplication ratio (validated by bench_compare.validate_propagation)
    prop = agg.propagation_summary()
    if prop is not None:
        out["propagation"] = prop
    sim.stop_all_nodes()
    return out


def fleet_scale_leg(n_nodes: int, n_ledgers: int, seed: int) -> dict:
    """One N-node consensus run for `bench.py --fleet-scale` (ISSUE 19;
    ROADMAP item 3's 50-100-node study): an n-node quorum over loopback
    channels with a seeded three-region latency matrix, closing
    n_ledgers ledgers under a light payment load. Loopback (not
    OVER_PEERS) on purpose — the scale leg measures consensus-message
    complexity (envelopes per slot, the O(n^2) flood baseline), slot
    convergence under geographic skew, and per-node memory; real-frame
    wire accounting stays with `--fleet`, which this leg would make
    O(n^2)-slow at N=50.

    per_node_rss_mb is the measured process RSS delta across the run
    divided by N: in-process nodes share one interpreter, so per-node
    self-reports all read the same RSS (footprint_table documents the
    same caveat). Legs run in one process, so later legs inherit the
    allocator arena of earlier ones — the delta still tracks each N's
    incremental growth because freed blocks are reused first."""
    import gc
    from stellar_core_tpu.simulation import topologies
    from stellar_core_tpu.simulation.geography import LatencyMatrix
    from stellar_core_tpu.simulation.simulation import Simulation
    from stellar_core_tpu.testing import AppLedgerAdapter
    from stellar_core_tpu.util import rnd
    from stellar_core_tpu.util.footprint import process_stats

    rnd.reseed(seed ^ n_nodes)
    gc.collect()
    rss0 = process_stats()["rss_mb"]
    sim = topologies.core(
        n_nodes, max(2, (n_nodes * 2 + 1) // 3),
        mode=Simulation.OVER_LOOPBACK,
        cfg_tweak=lambda c: (setattr(c, "TRACE_ENABLED", True),
                             setattr(c, "DATABASE", "sqlite3://:memory:")))
    matrix = LatencyMatrix(sorted(sim.nodes), "three-region", seed=seed)
    sim.apply_latency_matrix(matrix)
    sim.start_all_nodes()
    sim.crank_until(lambda: sim.have_all_externalized(2), 200000)
    first = next(iter(sim.nodes.values())).app
    ad = AppLedgerAdapter(first)
    root = ad.root_account()
    base_seq = ad.seq_num(root.account_id)
    for i in range(4):
        first.submit_transaction(root.tx(
            [root.op_payment(root.account_id, 1 + i)],
            seq=base_seq + 1 + i))
    target = 1 + n_ledgers   # genesis is seq 1; n_ledgers consensus closes
    ok = sim.crank_until(lambda: sim.have_all_externalized(target),
                         200000 + 20000 * n_nodes)
    agg = sim.fleet()
    stats = agg.fleet_stats()
    rss1 = process_stats()["rss_mb"]
    scp = stats.get("scp")
    fpt = stats.get("footprint")
    per_node_rss = round(max(0.0, rss1 - rss0) / n_nodes, 3)
    if fpt is not None:
        # replace the shared-interpreter self-report with the measured
        # scaling signal (see docstring)
        fpt["per_node_rss_mb"] = per_node_rss
    leg = {
        "nodes": n_nodes,
        "platform": "fleet-n%d" % n_nodes,
        "converged": bool(ok),
        "ledgers_closed": min(
            n.app.ledger_manager.last_closed_ledger_num()
            for n in sim.nodes.values()) - 1,
        "per_node_rss_mb": per_node_rss,
        "rss_delta_mb": round(max(0.0, rss1 - rss0), 3),
        "externalize_skew_p95_ms": round(
            stats["summary"]["externalize_skew_p95_s"] * 1e3, 3),
        "envelopes_per_slot": scp["envelopes_per_slot"]
        if scp is not None else None,
        "latency": {"profile": matrix.profile, "seed": matrix.seed,
                    "regions": sorted(set(matrix.region.values()))},
        "scp": scp,
        "footprint": fpt,
    }
    sim.stop_all_nodes()
    gc.collect()
    return leg


def fleet_scale_main(argv) -> int:
    """`bench.py --fleet-scale [--sizes 10,25,50] [--ledgers 6]
    [--record] [--history PATH] [--tolerance T] [--out FILE]`: the
    N-vs-cost scaling leg (ISSUE 19). One in-process simulation per
    fleet size, each emitting three gated records under its own
    `fleet-n<N>` platform key — `per_node_rss_mb` (lower; the N-vs-RSS
    curve), `externalize_skew_p95_ms` (lower; convergence under the
    three-region matrix), and `envelopes_per_slot` (lower; the O(n^2)
    flood baseline ROADMAP item 1's BLS quorum certificates must beat)
    — plus the worst ballot round count. Pure Python (no jax import):
    safe to run inline; never touches the device relay."""
    import argparse
    bc = _bench_compare_mod()
    ap = argparse.ArgumentParser(prog="bench.py --fleet-scale")
    ap.add_argument("--fleet-scale", action="store_true")
    ap.add_argument("--sizes", default="10,25,50")
    ap.add_argument("--ledgers", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0x5CA1E)
    ap.add_argument("--record", action="store_true")
    ap.add_argument("--history",
                    default=os.path.join(_REPO, "bench", "history.jsonl"))
    ap.add_argument("--tolerance", type=float, default=0.25)
    ap.add_argument("--out", help="also write the block to this file")
    args = ap.parse_args(argv)
    sizes = sorted({int(x) for x in args.sizes.split(",") if x.strip()})

    src = "bench.py --fleet-scale"
    legs = {}
    errors = {}
    records = []
    for n in sizes:
        try:
            leg = fleet_scale_leg(n, args.ledgers, args.seed)
        except Exception as e:                      # noqa: BLE001
            errors["n%d" % n] = "%s: %s" % (type(e).__name__, e)
            continue
        legs[str(n)] = leg
        plat = leg["platform"]
        records.append(bc.make_record(
            "per_node_rss_mb", "MB", leg["per_node_rss_mb"], plat,
            "lower", src))
        records.append(bc.make_record(
            "externalize_skew_p95_ms", "ms",
            leg["externalize_skew_p95_ms"], plat, "lower", src))
        records.extend(bc.scp_records(leg.get("scp"), plat, src))

    out = {
        "metric": "fleet_scale_envelopes_per_slot",
        "unit": "envelopes",
        "value": max((leg["envelopes_per_slot"] or 0.0
                      for leg in legs.values()), default=0.0),
        "platform": "fleet-scale",
        "sizes": sizes,
        "ledgers": args.ledgers,
        "seed": args.seed,
        "legs": legs,
    }
    if errors:
        out["errors"] = errors
    out["records"] = records
    history = bc.load_history(args.history)
    report = bc.compare(records, history, tolerance=args.tolerance)
    if args.record:
        commit = _git_commit()
        now = int(time.time())
        for rec in records:
            if rec.get("at_unix") is None:
                rec["at_unix"] = now
            if rec.get("commit") is None:
                rec["commit"] = commit
        report["recorded"] = bc.append_history(args.history, records)
    out["compare"] = report
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
    print(json.dumps(out, indent=1, sort_keys=True))
    # a leg that produced no data is a failure, not a green gate
    if not legs or errors:
        return 1
    return 1 if report["regressions"] else 0


def fleet_verify_child(chunk: int = 8192, chunks: int = 3,
                       iters: int = 4) -> dict:
    """One fleet-verify measurement at the CURRENT process's device
    count (the orchestrator forces it per child via
    `--xla_force_host_platform_device_count=N`): a `chunks`-chunk drain
    through the production TpuSigVerifier — sharded mesh dispatch,
    double-buffered staging, cockpit-driven warmup — timed end to end.

    warm_restart_s is construction → warmed → first full-rate drain
    complete, i.e. the time a restarted node pays before verifying at
    full rate (near-zero compile inside when the persistent XLA cache
    is warm)."""
    import jax
    from stellar_core_tpu.crypto.batch_verifier import (
        TpuSigVerifier, VerifierStats)

    n_devices = jax.device_count()
    n = chunk * chunks
    pubs, sigs, msgs = _example_batch(n, n_keys=64)
    triples = list(zip(pubs, sigs, msgs))

    t0 = time.perf_counter()
    v = TpuSigVerifier(shard_threshold=min(chunk, 2048))
    v.BUCKETS = (chunk,)
    v.stats = VerifierStats()
    # cockpit evidence for the adaptive plan: this mix is all `chunk`-
    # sized buckets, so warmup compiles exactly one shape
    v.stats.record_bucket_dispatch(chunk, chunk, 0)
    v.save_warmup_plan()
    v.warmup(wait=True)
    first = v.verify_many(triples)
    warm_restart_s = time.perf_counter() - t0
    assert all(first), "fleet verify rejected valid signatures"

    best = 0.0
    for _ in range(iters):
        t1 = time.perf_counter()
        ok = v.verify_many(triples)
        dt = time.perf_counter() - t1
        assert all(ok)
        best = max(best, n / dt)
    j = v.stats.to_json()
    return {
        "devices": n_devices,
        "platform": jax.devices()[0].platform,
        "chunk": chunk,
        "drain_sigs": n,
        "fleet_sigs_per_s": round(best, 1),
        "per_device_sigs_per_s": round(best / n_devices, 1),
        "warm_restart_s": round(warm_restart_s, 3),
        "warmup_source": j["warmup"]["source"],
        "warmup_buckets_s": {b: info["seconds"] for b, info in
                             j["warmup"]["buckets"].items()},
        "staging": j["staging"],
        "devices_detail": j["devices"],
    }


def _spawn_fleet_child(n_devices: int, chunk: int,
                       chunks: int) -> subprocess.Popen:
    env = _scrubbed_cpu_env()
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=%d"
                        % n_devices).strip()
    return _spawn("import bench, json; "
                  "print('FLEETV_JSON ' + json.dumps("
                  "bench.fleet_verify_child(chunk=%d, chunks=%d)))"
                  % (chunk, chunks), env)


def fleet_verify_main(argv) -> int:
    """`bench.py --fleet-verify [--devices 1,2,4] [--chunk 8192]
    [--record] [--history PATH] [--tolerance T] [--out FILE]`: the
    multi-device verify leg (ISSUE 11; ROADMAP item 1). One child
    process per device count, each on a forced virtual-CPU fleet
    (`--xla_force_host_platform_device_count=N` — the same fake-device
    contract tier-1 uses), running the SAME batch mix through the
    production sharded drain. Emits `fleet_sigs_per_s` /
    `per_device_sigs_per_s` / `warm_restart_s` records under
    `verify-fleet-cpu<N>` platform keys, gated against
    bench/history.jsonl; the N_max/N_1 ratio lands as
    `fleet_verify_speedup`. Never touches the device relay."""
    import argparse
    bc = _bench_compare_mod()
    ap = argparse.ArgumentParser(prog="bench.py --fleet-verify")
    ap.add_argument("--fleet-verify", action="store_true")
    ap.add_argument("--devices", default="1,2,4")
    ap.add_argument("--chunk", type=int, default=8192)
    ap.add_argument("--chunks", type=int, default=3)
    ap.add_argument("--record", action="store_true")
    ap.add_argument("--history",
                    default=os.path.join(_REPO, "bench", "history.jsonl"))
    ap.add_argument("--tolerance", type=float, default=0.25)
    ap.add_argument("--out", help="also write the block to this file")
    args = ap.parse_args(argv)
    counts = sorted({int(x) for x in args.devices.split(",") if x.strip()})

    legs = {}
    errors = {}
    for nd in counts:
        proc = _spawn_fleet_child(nd, args.chunk, args.chunks)
        # budget: one cold kernel compile (~150s on this container) +
        # the timed drains; stall-kill well past that
        deadline = time.time() + 900
        while time.time() < deadline and proc.poll() is None:
            time.sleep(1.0)
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
            errors["fleet_cpu%d" % nd] = "killed at deadline"
            continue
        got, err = _harvest(proc, "FLEETV_JSON")
        if err:
            errors["fleet_cpu%d" % nd] = err
        else:
            legs[str(nd)] = got

    out = {
        "metric": "fleet_verify_sigs_per_s",
        "unit": "sigs/s",
        "value": max((leg["fleet_sigs_per_s"] for leg in legs.values()),
                     default=0.0),
        "platform": "verify-fleet-cpu",
        "chunk": args.chunk,
        "drain_sigs": args.chunk * args.chunks,
        "fleet_verify": legs,
    }
    if "1" in legs and len(legs) > 1:
        top = str(max(int(k) for k in legs))
        out["fleet_speedup"] = round(
            legs[top]["fleet_sigs_per_s"] / legs["1"]["fleet_sigs_per_s"],
            3)
        out["fleet_speedup_devices"] = int(top)
    if errors:
        out["errors"] = errors

    src = "bench.py --fleet-verify"
    records = bc.fleet_verify_records(out.get("fleet_verify"), src)
    if "fleet_speedup" in out:
        records.append(bc.make_record(
            "fleet_verify_speedup", "x", out["fleet_speedup"],
            "verify-fleet-cpu", "higher", src))
    out["records"] = records
    history = bc.load_history(args.history)
    report = bc.compare(records, history, tolerance=args.tolerance)
    if args.record:
        commit = _git_commit()
        now = int(time.time())
        for rec in records:
            if rec.get("at_unix") is None:
                rec["at_unix"] = now
            if rec.get("commit") is None:
                rec["commit"] = commit
        report["recorded"] = bc.append_history(args.history, records)
    out["compare"] = report
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
    print(json.dumps(out, indent=1, sort_keys=True))
    # a leg that produced no data is a failure, not a green gate — an
    # all-children-wedged run must never read as "no regressions"
    if not legs or errors:
        return 1
    return 1 if report["regressions"] else 0


def hash_bench_child(shapes=((256, 2), (1024, 4), (4096, 2),
                             (4096, 4)), iters: int = 5) -> dict:
    """Batched-SHA-256 kernel legs (ISSUE 12), one per (lanes × blocks)
    dispatch shape, in the CURRENT process (the orchestrator spawns
    this in a scrubbed CPU child — never touches the device relay).
    Each leg times the jit'd kernel on messages that exactly fill the
    shape (`msg_bytes = blocks*64 - 9`), best-of-`iters`, against the
    single-core hashlib rate over the same batch."""
    import jax
    import numpy as np
    from stellar_core_tpu.ops.sha256 import (
        hash_blocks_jit, pad_messages_np, sha256_batch_host,
    )
    platform = jax.devices()[0].platform
    out = {"platform": platform, "kernel": {}, "host": {}}
    host_best = 0.0
    for lanes, blocks in shapes:
        msg_bytes = blocks * 64 - 9
        msgs = [bytes([i & 0xFF]) * msg_bytes for i in range(lanes)]
        words, counts = pad_messages_np(msgs, blocks)
        words_d, counts_d = (np.asarray(words), np.asarray(counts))
        t_c = time.perf_counter()
        first = np.asarray(hash_blocks_jit(words_d, counts_d))
        compile_s = time.perf_counter() - t_c
        from stellar_core_tpu.ops.sha256 import digests_to_bytes
        assert digests_to_bytes(first) == sha256_batch_host(msgs), \
            "kernel digests diverged from hashlib"
        best = 0.0
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(hash_blocks_jit(words_d, counts_d))
            dt = time.perf_counter() - t0
            best = max(best, lanes / dt)
        # host leg over the same batch: hashlib per message
        t0 = time.perf_counter()
        sha256_batch_host(msgs)
        host_rate = lanes / (time.perf_counter() - t0)
        key = "%dx%d" % (lanes, blocks)
        out["kernel"][key] = {
            "platform": platform, "lanes": lanes, "blocks": blocks,
            "msg_bytes": msg_bytes, "compile_s": round(compile_s, 2),
            "hash_msgs_per_s": round(best, 1),
            "hash_bytes_per_s": round(best * msg_bytes, 1),
            "host_msgs_per_s": round(host_rate, 1),
            "vs_host": round(best / host_rate, 3) if host_rate else None,
        }
        host_best = max(host_best, host_rate * msg_bytes)
    out["host"] = {"hash_bytes_per_s": round(host_best, 1)}
    return out


def checkpoint_bench(n_ledgers: int = 20, n_verifies: int = 200) -> dict:
    """Checkpoint/light-client leg (ISSUE 12): a standalone bucketed
    node closes `n_ledgers` under load with the incremental Merkle root
    checked against the from-scratch oracle at EVERY close, then serves
    a signed checkpoint + membership proofs and times
    `light_client_verify` (pure function — the light client's whole
    cost). Pure Python (no jax import): safe to run inline."""
    import json as _json
    import shutil
    import tempfile

    from stellar_core_tpu.ledger.state_commitment import (
        light_client_verify,
    )
    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.config import Config
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    from stellar_core_tpu.util import rnd
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    from stellar_core_tpu.xdr import LedgerKey

    rnd.reseed(0x4A54)
    tmp = tempfile.mkdtemp(prefix="sct-hashbench-")
    try:
        cfg = Config.test_config(77)
        cfg.DATABASE = "sqlite3://:memory:"
        cfg.STATE_CHECKPOINT_INTERVAL = 4
        cfg.INVARIANT_CHECKS = []
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
        app.enable_buckets(os.path.join(tmp, "buckets"))
        app.start()
        lg = LoadGenerator(app)
        lg.generate_accounts(20)
        app.manual_close()
        sce = app.state_commitment
        bl = app.bucket_manager.bucket_list
        oracle_equal = True
        update_ms = []
        for _ in range(n_ledgers):
            lg.generate_payments(10)
            app.clock.set_virtual_time(app.clock.now() + 1.0)
            t0 = time.perf_counter()
            app.manual_close()
            update_ms.append((time.perf_counter() - t0) * 1e3)
            if sce.root != sce.from_scratch_root(bl):
                oracle_equal = False
        cp = sce.checkpoint()
        key = LedgerKey.account(app.network_root_key().public_key)
        proof = sce.prove_entry(key)
        assert cp is not None and proof is not None
        net = cfg.network_id
        verify_s = []
        for _ in range(n_verifies):
            t0 = time.perf_counter()
            ok, reason = light_client_verify(proof, cp, net)
            verify_s.append(time.perf_counter() - t0)
            assert ok, reason
        verify_s.sort()
        update_ms.sort()
        m = app.metrics.to_json()
        upd = m.get("commitment.update-ms", {})
        return {
            "ledgers": n_ledgers,
            "oracle_equal": oracle_equal,
            "checkpoints": m.get("commitment.checkpoint.emitted",
                                 {}).get("count", 0),
            "proof_bytes": len(_json.dumps(proof)),
            "verify_p50_ms": round(
                verify_s[len(verify_s) // 2] * 1e3, 4),
            "verify_p95_ms": round(
                verify_s[int(len(verify_s) * 0.95)] * 1e3, 4),
            "verifies": n_verifies,
            # incremental root update cost per close (the engine's own
            # histogram; real elapsed ms)
            "update_p50_ms": round(upd.get("median", 0.0), 3),
            "update_p95_ms": round(upd.get("p95", 0.0), 3),
            "leaves_changed_mean": round(
                m.get("commitment.leaves-changed", {}).get("mean", 0.0),
                2),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _spawn_hash_child() -> subprocess.Popen:
    return _spawn("import bench, json; "
                  "print('HASH_JSON ' + json.dumps("
                  "bench.hash_bench_child()))", _scrubbed_cpu_env())


def hash_main(argv) -> int:
    """`bench.py --hash [--record] [--history PATH] [--tolerance T]
    [--out FILE] [--no-replay]`: the batched-hashing leg (ISSUE 12).
    Kernel throughput per (lanes × blocks) shape runs in a scrubbed CPU
    child (never touches the device relay); the checkpoint/light-client
    leg runs inline; unless --no-replay, a CPU replay leg runs in a
    child so the artifact carries the close `phase_breakdown` whose
    `close.bucket_add` / `close.header_hash` self-times the ISSUE 12
    acceptance compares against BENCH_r08. Records gate against
    bench/history.jsonl; exit 1 on regression or on a failed leg."""
    import argparse
    bc = _bench_compare_mod()
    ap = argparse.ArgumentParser(prog="bench.py --hash")
    ap.add_argument("--hash", action="store_true")
    ap.add_argument("--record", action="store_true")
    ap.add_argument("--history",
                    default=os.path.join(_REPO, "bench", "history.jsonl"))
    ap.add_argument("--tolerance", type=float, default=0.25)
    ap.add_argument("--out", help="also write the block to this file")
    ap.add_argument("--no-replay", action="store_true")
    args = ap.parse_args(argv)

    errors = {}
    hb = None
    proc = _spawn_hash_child()
    deadline = time.time() + 900
    while time.time() < deadline and proc.poll() is None:
        time.sleep(1.0)
    if proc.poll() is None:
        proc.kill()
        proc.communicate()
        errors["hash_kernel"] = "killed at deadline"
    else:
        hb, err = _harvest(proc, "HASH_JSON")
        if err:
            errors["hash_kernel"] = err
    if hb is None:
        hb = {"platform": "none", "kernel": {}, "host": {}}
    try:
        hb["checkpoint"] = checkpoint_bench()
    except Exception as e:   # noqa: BLE001 - recorded, not swallowed
        errors["checkpoint_leg"] = repr(e)[:400]

    out = {
        "metric": "hash_bench",
        "unit": "bytes/s",
        "value": max((leg["hash_bytes_per_s"]
                      for leg in hb.get("kernel", {}).values()),
                     default=0.0),
        "platform": "hash-%s" % hb.get("platform", "none"),
        "hash_bench": hb,
    }

    if not args.no_replay:
        # CPU replay leg: the phase_breakdown evidence for the
        # bucket_add/header_hash shrink. Embedded for the record, NOT
        # normalized into gating records here — the full-leg replay
        # history keys gate via the main bench, not the hash leg.
        proc = _spawn_replay(_scrubbed_cpu_env(), "cpu")
        deadline = time.time() + 600
        while time.time() < deadline and proc.poll() is None:
            time.sleep(1.0)
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
            errors["replay_cpu"] = "killed at deadline"
        else:
            rep, err = _harvest(proc, "REPLAY_JSON")
            if err:
                errors["replay_cpu"] = err
            else:
                out["replay_cpu"] = rep
                phases = rep.get("phase_breakdown", {}).get("phases", {})
                out["close_hash_phases"] = {
                    k: phases[k] for k in
                    ("close.bucket_add", "close.header_hash",
                     "close.result_hash", "close.commitment")
                    if k in phases}

    src = "bench.py --hash"
    # the leg's own differential oracle: a diverged incremental Merkle
    # root must fail the gate AND never be recorded as a baseline
    # (validate_hash_bench enforces the same on committed artifacts)
    oracle_ok = hb.get("checkpoint", {}).get("oracle_equal") is True
    if not oracle_ok:
        errors.setdefault(
            "checkpoint_oracle",
            "incremental Merkle root diverged from the from-scratch "
            "oracle — records withheld from history")
    records = bc.hash_bench_records(hb, src)
    out["records"] = records
    history = bc.load_history(args.history)
    report = bc.compare(records, history, tolerance=args.tolerance)
    if args.record and oracle_ok:
        commit = _git_commit()
        now = int(time.time())
        for rec in records:
            if rec.get("at_unix") is None:
                rec["at_unix"] = now
            if rec.get("commit") is None:
                rec["commit"] = commit
        report["recorded"] = bc.append_history(args.history, records)
    out["compare"] = report
    if errors:
        out["errors"] = errors
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
    print(json.dumps(out, indent=1, sort_keys=True))
    if not hb.get("kernel") or "checkpoint" not in hb or errors:
        return 1
    return 1 if report["regressions"] else 0


def _bucketdb_seed_state(app, n_accounts: int, seed: int,
                         level: int = 6) -> list:
    """Seeded cold-state generator (ISSUE 14): install `n_accounts`
    deterministic accounts as one deep-level bucket WITHOUT closing
    ledgers — the bucket file, its content hash, the sorted key index
    and the bloom filter are all built in one streamed pass, so 10^6
    accounts never sit in memory as Python entry objects. The installed
    bucket is file-backed only (a slim Bucket with no resident
    entries): every later read exercises the sidecar-index + pread
    path for real. Returns the 32-byte account key list (payment
    destinations for the traffic legs)."""
    import hashlib as _hashlib

    from stellar_core_tpu.bucket.bucket import Bucket, entry_record
    from stellar_core_tpu.bucket.bucket_index import (
        BloomFilter, BucketIndex, key_fingerprint, sidecar_path,
    )
    from stellar_core_tpu.transactions.account_helpers import (
        make_account_entry,
    )
    from stellar_core_tpu.xdr import (
        BucketEntry, PublicKey, ledger_entry_key,
    )

    bm = app.bucket_manager
    proto = app.ledger_manager.lcl_header.ledgerVersion
    # account ids sorted up front: LIVE bucket entries order by
    # (type, accountID XDR), which for same-type keys is raw pubkey order
    keys = sorted(
        _hashlib.sha256(b"bucketdb-bench:%d:%d" % (seed, i)).digest()
        for i in range(n_accounts))
    h = _hashlib.sha256()
    tmp_path = os.path.join(bm.bucket_dir, ".seed-%d.tmp" % n_accounts)
    idx_keys, ordinals, offsets, lengths = [], [], [], []
    bloom = BloomFilter.for_capacity(
        n_accounts, app.config.BUCKETDB_BLOOM_BITS_PER_KEY)
    off = 0
    with open(tmp_path, "wb") as fh:
        meta = entry_record(BucketEntry.meta(proto))
        fh.write(meta)
        h.update(meta)
        off += len(meta)
        for ordinal, kb32 in enumerate(keys, start=1):
            e = make_account_entry(PublicKey.ed25519(kb32), 10**9, 0, 1)
            rec = entry_record(BucketEntry.live(e))
            fh.write(rec)
            h.update(rec)
            lk = ledger_entry_key(e).to_xdr()
            idx_keys.append(lk)
            ordinals.append(ordinal)
            offsets.append(off + 8)        # 4B record mark + 4B union disc
            lengths.append(len(rec) - 8)
            bloom.add(key_fingerprint(lk))
            off += len(rec)
    bucket_hash = h.digest()
    path = bm.bucket_filename(bucket_hash)
    os.replace(tmp_path, path)
    slim = Bucket((), hash_=bucket_hash, path=path)
    BucketIndex(bucket_hash, idx_keys, ordinals, offsets, lengths,
                bloom).save(sidecar_path(path))
    with bm._lock:
        bm._shared[bucket_hash] = slim
    # deep level: nothing spills into (or merges) level 6 within the
    # bench's few dozen closes, so the cold state stays put while the
    # close path hashes the list over it every close
    bm.bucket_list.levels[level].curr = slim
    return keys


def _bucketdb_leg(n_accounts: int, senders: int, closes: int,
                  surge_closes: int, seed: int) -> dict:
    """One scale point of the --bucketdb latency-flatness gate: a
    standalone node over `n_accounts` of seeded bucket-backed cold
    state, closing `closes` ledgers of uniform-random payments into the
    cold set (every destination read is a bloom-filtered index probe)
    and `surge_closes` of hot-key-skewed traffic for the prefetch
    hit-rate gate."""
    import random as _random
    import shutil
    import tempfile

    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.config import Config
    from stellar_core_tpu.testing import AppLedgerAdapter, TestAccount
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    from stellar_core_tpu.xdr import PublicKey

    tmp = tempfile.mkdtemp(prefix="sct-bucketdb-")
    try:
        cfg = Config.test_config(0)
        cfg.DATABASE = "sqlite3://:memory:"
        cfg.INVARIANT_CHECKS = []
        cfg.TESTING_UPGRADE_MAX_TX_SET_SIZE = 10_000
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
        app.enable_buckets(os.path.join(tmp, "buckets"))
        app.start()
        # the commitment engine would Merkle an empty root over the
        # slim (non-resident) seeded bucket — disabled for the leg
        # (docs/perf-replay.md#million-account-methodology)
        app.state_commitment = None
        assert app.ledger_manager.root.bucket_backed()
        cold_keys = _bucketdb_seed_state(app, n_accounts, seed)

        adapter = AppLedgerAdapter(app)
        root = adapter.root_account()
        sender_sks = [SecretKey.from_seed(
            bytes([13, i & 0xFF, (i >> 8) & 0xFF, seed & 0xFF] + [29] * 28))
            for i in range(senders)]
        for lo in range(0, senders, 100):
            app.submit_transaction(root.tx(
                [root.op_create_account(sk.public_key, 10**10)
                 for sk in sender_sks[lo:lo + 100]]))
            app.manual_close()
        sender_accs = [TestAccount(adapter, sk) for sk in sender_sks]

        lm = app.ledger_manager
        bdb = app.bucket_manager.bucketdb
        rnd = _random.Random(seed)
        # warm pass: the big sidecar loads ONCE here (index load cost is
        # startup, not steady-state close latency)
        bdb.lookup(_cold_account_key_xdr(cold_keys[0]))

        lm.apply_stats.reset()
        bdb.stats.reset()
        walls = []
        for c in range(closes):
            app.clock.set_virtual_time(app.clock.now() + 1)
            for s in sender_accs:
                dest = PublicKey.ed25519(
                    cold_keys[rnd.randrange(n_accounts)])
                app.submit_transaction(
                    s.tx([s.op_payment(dest, 100)]))
            t0 = time.perf_counter()
            app.manual_close()
            walls.append((time.perf_counter() - t0) * 1e3)
        uniform_reads = lm.apply_stats.to_json()["state_reads"]
        sql_lookups = sum(uniform_reads["lookups"].values())

        # surge: hot-key skew — 80% of payments hammer one destination,
        # 20% still land in the cold set (the prefetch bulk-warm must
        # keep covering both)
        lm.apply_stats.reset()
        hot = PublicKey.ed25519(cold_keys[0])
        for c in range(surge_closes):
            app.clock.set_virtual_time(app.clock.now() + 1)
            for i, s in enumerate(sender_accs):
                dest = hot if i % 5 else PublicKey.ed25519(
                    cold_keys[rnd.randrange(n_accounts)])
                app.submit_transaction(s.tx([s.op_payment(dest, 100)]))
            app.manual_close()
        surge_stats = lm.apply_stats.to_json()
        sql_lookups += sum(
            surge_stats["state_reads"]["lookups"].values())

        walls_sorted = sorted(walls)
        p50 = walls_sorted[len(walls_sorted) // 2]
        bstats = bdb.stats
        out = {
            "accounts": n_accounts,
            "senders": senders,
            "closes": closes,
            "close_ms_p50": round(p50, 3),
            "close_ms_mean": round(sum(walls) / len(walls), 3),
            "close_ms_max": round(max(walls), 3),
            "surge": {
                "closes": surge_closes,
                "prefetch_hit_rate_pct": round(
                    100.0 * surge_stats["prefetch_hit_rate"], 2),
            },
            "bloom_fp_pct": round(
                100.0 * bstats.false_positive_rate(), 4),
            "bucketdb": bdb.stats.to_json(),
            "sql_point_lookups": sql_lookups,
        }
        app.stop()
        app.bucket_manager.shutdown()
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _cold_account_key_xdr(kb32: bytes) -> bytes:
    from stellar_core_tpu.xdr import LedgerKey, PublicKey
    return LedgerKey.account(PublicKey.ed25519(kb32)).to_xdr()


def bucketdb_bench(small: int = 10**4, large: int = 10**6,
                   senders: int = 40, closes: int = 16,
                   surge_closes: int = 8, seed: int = 4242,
                   progress=None) -> dict:
    """`bench.py --bucketdb` (ISSUE 14): close-latency flatness from
    `small` to `large` seeded accounts with bucket-backed reads, plus
    the surge prefetch-hit-rate and bloom false-positive gates. Pure
    CPU/IO — safe to run inline (no jax import)."""
    legs = {}
    for name, n in (("small", small), ("large", large)):
        legs[name] = _bucketdb_leg(n, senders, closes, surge_closes, seed)
        if progress is not None:
            progress(name)
    ratio = legs["large"]["close_ms_p50"] / \
        max(1e-9, legs["small"]["close_ms_p50"])
    return {
        "small": legs["small"],
        "large": legs["large"],
        "latency_ratio": round(ratio, 4),
        "prefetch_hit_rate_pct":
            legs["large"]["surge"]["prefetch_hit_rate_pct"],
        "bloom_fp_pct": legs["large"]["bloom_fp_pct"],
        "sql_point_lookups": legs["small"]["sql_point_lookups"] +
            legs["large"]["sql_point_lookups"],
    }


def bucketdb_main(argv) -> int:
    """`bench.py --bucketdb [--small N] [--large N] [--record]
    [--history PATH] [--tolerance T] [--out FILE]`: the million-account
    BucketDB gate (ISSUE 14). Hard gates (exit 1): close-latency p50
    within 1.25x from --small to --large accounts, surge prefetch
    hit-rate >= 95%, bloom false positives <= 5%, and ZERO apply-path
    SQL point lookups across every measured close (cockpit-asserted).
    Records gate against bench/history.jsonl like every other leg."""
    import argparse
    bc = _bench_compare_mod()
    ap = argparse.ArgumentParser(prog="bench.py --bucketdb")
    ap.add_argument("--bucketdb", action="store_true")
    ap.add_argument("--small", type=int, default=10**4)
    ap.add_argument("--large", type=int, default=10**6)
    ap.add_argument("--senders", type=int, default=40)
    ap.add_argument("--closes", type=int, default=16)
    ap.add_argument("--record", action="store_true")
    ap.add_argument("--history",
                    default=os.path.join(_REPO, "bench", "history.jsonl"))
    ap.add_argument("--tolerance", type=float, default=0.25)
    ap.add_argument("--out", help="also write the block to this file")
    args = ap.parse_args(argv)

    t0 = time.time()
    bd = bucketdb_bench(small=args.small, large=args.large,
                        senders=args.senders, closes=args.closes,
                        progress=lambda s: print(
                            "# bucketdb leg %s done (%.0fs)"
                            % (s, time.time() - t0), file=sys.stderr))
    errors = {}
    if bd["latency_ratio"] > 1.25:
        errors["latency_flatness"] = \
            "close p50 grew %.2fx from %d to %d accounts (gate 1.25x)" \
            % (bd["latency_ratio"], args.small, args.large)
    if bd["prefetch_hit_rate_pct"] < 95.0:
        errors["prefetch_hit_rate"] = \
            "surge prefetch hit-rate %.2f%% < 95%%" \
            % bd["prefetch_hit_rate_pct"]
    if bd["bloom_fp_pct"] > 5.0:
        errors["bloom_fp"] = "bloom false-positive rate %.3f%% > 5%%" \
            % bd["bloom_fp_pct"]
    if bd["sql_point_lookups"] != 0:
        errors["sql_point_lookups"] = \
            "%d apply-path SQL point lookups leaked (gate: zero)" \
            % bd["sql_point_lookups"]

    src = "bench.py --bucketdb"
    records = bc.bucketdb_records(bd, src)
    out = {
        "metric": "bucketdb_latency_ratio",
        "unit": "x",
        "value": bd["latency_ratio"],
        "platform": "bucketdb-cpu",
        "at_unix": int(t0),
        "bucketdb_bench": bd,
        "records": records,
    }
    history = bc.load_history(args.history)
    report = bc.compare(records, history, tolerance=args.tolerance)
    if args.record and not errors:
        commit = _git_commit()
        now = int(time.time())
        for rec in records:
            if rec.get("at_unix") is None:
                rec["at_unix"] = now
            if rec.get("commit") is None:
                rec["commit"] = commit
        report["recorded"] = bc.append_history(args.history, records)
    out["compare"] = report
    if errors:
        out["errors"] = errors
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
    print(json.dumps(out, indent=1, sort_keys=True))
    if errors:
        return 1
    return 1 if report["regressions"] else 0


def _bench_compare_mod():
    """The perf-regression ledger module (tools/bench_compare.py) —
    stdlib-only, never imports jax."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    from tools import bench_compare
    return bench_compare


def _git_commit() -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO,
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None
    except Exception:   # noqa: BLE001 - commit stamp is best-effort
        return None


def compare_leg() -> list:
    """Tiny deterministic CPU replay leg for the regression gate
    (ISSUE 6): seeded content, cpu backend, one checkpoint — the full
    bench compressed into seconds. Records key by platform "cpu-tiny" /
    "openssl-cpu-tiny", so they only ever gate against tiny-leg
    baselines, never against full-leg or device history. Pure Python
    (no jax import): safe to run inline and in tier-1."""
    bc = _bench_compare_mod()
    src = "bench.py --compare"
    r = replay_bench("cpu", n_checkpoints=1, txs_per_ledger=4,
                     sigs_per_tx=2, repeats=1)
    recs = [
        bc.make_record("replay_ledgers_per_sec", "ledgers/s",
                       r["ledgers_per_sec"], "cpu-tiny", "higher", src),
        bc.make_record("replay_txs_per_sec", "txs/s",
                       r["txs_per_sec"], "cpu-tiny", "higher", src),
        bc.make_record("replay_wall_s", "s", r["wall_s"],
                       "cpu-tiny", "lower", src),
        bc.make_record("replay_crypto_s", "s", r["crypto_s"],
                       "cpu-tiny", "lower", src),
        bc.make_record("cpu_openssl_baseline_sigs_per_sec", "sigs/s",
                       round(cpu_baseline_rate(500), 1),
                       "openssl-cpu-tiny", "higher", src),
    ]
    # per-op apply costs gate under the same tiny platform key (ISSUE 9)
    recs.extend(bc.apply_breakdown_records(
        r.get("apply_breakdown"), "cpu-tiny", src))
    return recs


def compare_main(argv) -> int:
    """`bench.py --compare [--record] [--input FILE] [--history PATH]
    [--tolerance T]`: diff a current run against the best committed
    record per (metric, platform) in bench/history.jsonl; exit 1 on any
    regression beyond tolerance. Without `--input` the tiny CPU replay
    leg runs inline; with it, an existing bench-output JSON (or a
    {"records": [...]} blob) is normalized instead. `--record` appends
    the current records (commit- and time-stamped) to the history."""
    import argparse
    bc = _bench_compare_mod()
    ap = argparse.ArgumentParser(prog="bench.py --compare")
    ap.add_argument("--compare", action="store_true")
    ap.add_argument("--record", action="store_true")
    ap.add_argument("--input")
    ap.add_argument("--history",
                    default=os.path.join(_REPO, "bench", "history.jsonl"))
    ap.add_argument("--tolerance", type=float, default=0.1)
    args = ap.parse_args(argv)
    if args.input:
        with open(args.input) as fh:
            blob = json.load(fh)
        current = bc.normalize_any(blob, os.path.basename(args.input))
    else:
        current = compare_leg()
    history = bc.load_history(args.history)
    report = bc.compare(current, history, tolerance=args.tolerance)
    if args.record:
        commit = _git_commit()
        now = int(time.time())
        for rec in current:
            if rec.get("at_unix") is None:
                rec["at_unix"] = now
            if rec.get("commit") is None:
                rec["commit"] = commit
        report["recorded"] = bc.append_history(args.history, current)
    print(json.dumps(report, indent=1, sort_keys=True))
    return 1 if report["regressions"] else 0


def scenario_main(argv) -> int:
    """`bench.py --scenario NAME [--seed N] [--scale tier1|soak]
    [--record] [--history PATH] [--tolerance T] [--out FILE]`: run one
    scenario from the scenario lab (stellar_core_tpu/testing/scenarios.py
    — churn / flood / partition / surge / overload / checkpoint, or
    `suite` for all) and emit its
    fleet bench block. The block's normalized `records` (platform keys
    `scenario-<name>`) are gated against bench/history.jsonl exactly like
    perf records: exit 1 on any regression beyond tolerance (default 0.5
    — slot latencies are wall-clock and jittery; the virtual-clock
    recovery times are tight). `--record` appends the records to the
    history. Pure Python (no jax import): safe to run inline."""
    import argparse
    bc = _bench_compare_mod()
    ap = argparse.ArgumentParser(prog="bench.py --scenario")
    ap.add_argument("--scenario", required=True,
                    help="churn|flood|partition|surge|overload|"
                         "checkpoint|suite")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--scale", choices=("tier1", "soak"), default="tier1")
    ap.add_argument("--record", action="store_true")
    ap.add_argument("--history",
                    default=os.path.join(_REPO, "bench", "history.jsonl"))
    ap.add_argument("--tolerance", type=float, default=0.5)
    ap.add_argument("--out", help="also write the block to this file")
    args = ap.parse_args(argv)
    from stellar_core_tpu.testing.scenarios import run_scenario, run_suite
    if args.scenario == "suite":
        block = run_suite(seed=args.seed, scale=args.scale)
    else:
        block = run_scenario(args.scenario, seed=args.seed,
                             scale=args.scale)
    current = list(block["records"])
    history = bc.load_history(args.history)
    report = bc.compare(current, history, tolerance=args.tolerance)
    if args.record:
        commit = _git_commit()
        now = int(time.time())
        for rec in current:
            if rec.get("at_unix") is None:
                rec["at_unix"] = now
            if rec.get("commit") is None:
                rec["commit"] = commit
        report["recorded"] = bc.append_history(args.history, current)
    block["compare"] = report
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(block, fh, indent=1, sort_keys=True)
    print(json.dumps(block, indent=1, sort_keys=True))
    return 1 if report["regressions"] else 0


def _scrubbed_cpu_env() -> dict:
    # single source of truth for the axon-env scrub lives in __graft_entry__
    from __graft_entry__ import _scrubbed_env
    return _scrubbed_env(1)


def probe_device(timeout_s: float = 30.0) -> tuple:
    """Cheap relay-health probe: a child imports jax and lists devices
    under a hard timeout. Returns (device_present, info). Run BEFORE
    committing to a full device bench — the axon relay wedges for hours
    after killed JAX clients, and a wedged relay hangs init forever."""
    code = ("import jax, json; "
            "print('PROBE_JSON ' + json.dumps("
            "{'platform': jax.devices()[0].platform}))")
    proc = subprocess.Popen(
        [sys.executable, "-c", code], cwd=_REPO, env=dict(os.environ),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    t0 = time.time()
    while time.time() - t0 < timeout_s and proc.poll() is None:
        time.sleep(0.5)
    if proc.poll() is None:
        proc.kill()
        proc.communicate()
        return False, "probe timeout after %.0fs" % timeout_s
    got, err = _harvest(proc, "PROBE_JSON")
    if err:
        return False, err
    plat = got.get("platform")
    return plat in ("tpu", "axon"), "platform=%s" % plat


def _spawn(code: str, env: dict | None = None) -> subprocess.Popen:
    """Child-process spawner shared by every bench leg. Always sets the
    persistent compilation cache: makes recompiles (and the CPU fallback
    after the test suite has run) near-instant."""
    env = dict(os.environ if env is None else env)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(_REPO, ".jax_cache"))
    return subprocess.Popen(
        [sys.executable, "-c", code], cwd=_REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _spawn_child(env: dict, batch: int, iters: int) -> subprocess.Popen:
    return _spawn("import bench, json; "
                  "print('BENCH_JSON ' + json.dumps("
                  "bench.device_bench(batch=%d, iters=%d)))" % (batch, iters),
                  env)


def _spawn_full_device_child(partial_path: str) -> subprocess.Popen:
    return _spawn("import bench, json; "
                  "print('BENCH_JSON ' + json.dumps("
                  "bench.device_full_bench(%r)))" % partial_path)


def _harvest(proc: subprocess.Popen, prefix: str = "BENCH_JSON") -> tuple:
    """(result_dict | None, error_str | None); proc must have exited."""
    out, err_txt = proc.communicate()
    if proc.returncode != 0:
        return None, ("rc=%d: %s" % (proc.returncode,
                                     err_txt.strip()[-600:]))
    for line in out.splitlines():
        if line.startswith(prefix + " "):
            return json.loads(line[len(prefix) + 1:]), None
    return None, "no %s line in child output: %s" % (
        prefix, out.strip()[-300:])


def _spawn_replay(env: dict, backend: str,
                  mix: str = "multisig") -> subprocess.Popen:
    return _spawn("import bench, json; "
                  "print('REPLAY_JSON ' + json.dumps("
                  "bench.replay_bench(%r, mix=%r)))" % (backend, mix), env)


def parallel_close_bench(n_pairs: int = 300, ops_per_tx: int = 20,
                         rounds: int = 8) -> dict:
    """The conflict-graph parallel-close gate (ISSUE 13): identical
    conflict-light txsets (disjoint sender pairs, multi-op payment txs)
    closed by two native LedgerManagers — one pinned serial, one pinned
    parallel — comparing the ENGINE's tx-execution wall (`apply_ns`:
    cluster scheduling + apply only; parse/verify/fees/emission are
    identical serial work on both sides). Rounds interleave so ambient
    sandbox noise hits both modes alike; the signature cache is
    prewarmed so verify cost cannot masquerade as apply time. Pure
    Python + the native engine — no jax import."""
    import statistics

    from stellar_core_tpu.crypto.hashing import sha256
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.crypto.batch_verifier import CpuSigVerifier
    from stellar_core_tpu.herder.txset import TxSetFrame
    from stellar_core_tpu.ledger.ledger_manager import (
        LedgerCloseData, LedgerManager,
    )
    from stellar_core_tpu.testing import (
        TESTING_NETWORK_ID, TestAccount, root_secret_key,
    )
    from stellar_core_tpu.xdr import StellarValue, StellarValueExt

    class _Cfg:
        DATABASE = "in-memory"
        LEDGER_PROTOCOL_VERSION = 13
        GENESIS_TOTAL_COINS = 10 ** 17
        TESTING_UPGRADE_DESIRED_FEE = 100
        TESTING_UPGRADE_RESERVE = 5_000_000
        TESTING_UPGRADE_MAX_TX_SET_SIZE = 100_000
        NATIVE_PARALLEL_APPLY = True
        NATIVE_PARALLEL_WORKERS = 0
        network_id = TESTING_NETWORK_ID

    class _App:
        config = _Cfg()

        def network_root_key(self):
            return root_secret_key()

    class _Shim:
        def __init__(self, lm):
            self.lm = lm
            self.network_id = TESTING_NETWORK_ID

        def header(self):
            return self.lm.root.get_header()

        def seq_num(self, account_id):
            from stellar_core_tpu.xdr import LedgerKey
            e = self.lm.root.get_entry(LedgerKey.account(account_id))
            return e.data.value.seqNum if e is not None else 0

    def mk(mode):
        lm = LedgerManager(_App())
        lm.start_new_ledger()
        lm.use_native_apply = True
        lm.native_force_mode = mode
        shim = _Shim(lm)
        root = TestAccount(shim, root_secret_key())
        accs = [TestAccount(shim, SecretKey.from_seed(
            sha256(b"pcb%d" % i))) for i in range(2 * n_pairs)]

        def close(frames, prewarm=True):
            if prewarm:
                CpuSigVerifier().prewarm_many(
                    [(f.tx.sourceAccount.account_id.key_bytes,
                      f.signatures[0].signature, f.contents_hash())
                     for f in frames])
            header = lm.root.get_header()
            ts = TxSetFrame(TESTING_NETWORK_ID, lm.lcl_hash, frames)
            value = StellarValue(
                txSetHash=ts.get_contents_hash(),
                closeTime=header.scpValue.closeTime + 5,
                upgrades=[], ext=StellarValueExt(0, None))
            lm.close_ledger(
                LedgerCloseData(header.ledgerSeq + 1, ts, value))

        for lo in range(0, 2 * n_pairs, 100):
            close([root.tx([root.op_create_account(a.account_id, 10 ** 10)
                            for a in accs[lo:lo + 100]])], prewarm=False)
        return lm, accs, close

    envs = {m: mk(m) for m in ("serial", "parallel")}
    walls = {"serial": [], "parallel": []}
    for rnd in range(rounds):
        for mode in ("serial", "parallel"):
            lm, accs, close = envs[mode]
            frames = []
            for k in range(n_pairs):
                a, b = accs[2 * k], accs[2 * k + 1]
                frames.append(a.tx(
                    [a.op_payment(b.account_id, 100 + rnd)] * ops_per_tx))
                frames.append(b.tx(
                    [b.op_payment(a.account_id, 50 + rnd)] * ops_per_tx))
            close(frames)
            walls[mode].append(
                lm.apply_stats.clusters["last_apply_ms"])
    # ambient sandbox noise only ever ADDS time; the per-mode floor
    # over interleaved rounds is the noise-free cost estimate (the
    # bench's established best-of-repeats rationale)
    ser = min(walls["serial"])
    par = min(walls["parallel"])
    pstats = envs["parallel"][0].apply_stats.clusters
    return {
        "n_pairs": n_pairs, "ops_per_tx": ops_per_tx, "rounds": rounds,
        "serial_apply_ms": round(ser, 3),
        "parallel_apply_ms": round(par, 3),
        "serial_apply_ms_median": round(
            statistics.median(walls["serial"]), 3),
        "parallel_apply_ms_median": round(
            statistics.median(walls["parallel"]), 3),
        "serial_apply_ms_all": [round(x, 3) for x in walls["serial"]],
        "parallel_apply_ms_all": [round(x, 3) for x in walls["parallel"]],
        "parallel_apply_speedup": round(ser / par, 3) if par else 0.0,
        "clusters": pstats["last_count"],
        "workers": pstats["last_workers"],
        "parallel_closes": pstats["parallel_closes"],
    }


def replay_full_main(argv) -> int:
    """`bench.py --replay-full [--record] [--history PATH]
    [--tolerance T] [--out FILE]`: the full-coverage apply leg
    (ISSUE 13). Three measurements, each in a scrubbed CPU child /
    inline:

    - standard-mix replay (platform `cpu-stdmix`): conflict-light pairs
      + all 14 op types + fee bumps + muxed. ASSERTS zero
      `ledger.apply.native-bail.*` and zero Python-path closes, and
      that per-op ms records exist for the newly-covered op types.
    - legacy multisig replay (platform `cpu-apply-native`,
      history-comparable with BENCH_r08).
    - the parallel-close gate leg (platform `cpu-parallel-close`):
      engine apply-wall serial vs parallel on a conflict-light txset.
    """
    import argparse
    bc = _bench_compare_mod()
    ap = argparse.ArgumentParser(prog="bench.py --replay-full")
    ap.add_argument("--replay-full", action="store_true")
    ap.add_argument("--record", action="store_true")
    ap.add_argument("--history",
                    default=os.path.join(_REPO, "bench", "history.jsonl"))
    ap.add_argument("--tolerance", type=float, default=0.25)
    ap.add_argument("--out", help="also write the block to this file")
    args = ap.parse_args(argv)

    errors = {}
    out = {"metric": "replay_full", "unit": "ledgers/s", "value": 0.0}
    legs = {}
    for label, mx in (("standard", "standard"), ("multisig", "multisig")):
        proc = _spawn_replay(_scrubbed_cpu_env(), "cpu", mix=mx)
        deadline = time.time() + 600
        while time.time() < deadline and proc.poll() is None:
            time.sleep(1.0)
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
            errors["replay_" + label] = "killed at deadline"
            continue
        rep, err = _harvest(proc, "REPLAY_JSON")
        if err:
            errors["replay_" + label] = err
        else:
            legs[label] = rep
    std = legs.get("standard")
    if std is not None:
        out["value"] = std.get("ledgers_per_sec", 0.0)
        # the zero-bail + native-only acceptance (ISSUE 13): real
        # failures, not history comparisons
        if std.get("native_bails"):
            errors["native_bails"] = std["native_bails"]
        if std.get("python_closes"):
            errors["python_closes"] = std["python_closes"]
        per_op = std.get("apply_breakdown", {}).get("per_op_ms", {})
        missing = [op for op in
                   ("change-trust", "allow-trust", "manage-data",
                    "bump-sequence", "account-merge",
                    "manage-sell-offer", "manage-buy-offer",
                    "path-payment-strict-receive",
                    "path-payment-strict-send")
                   if op not in per_op]
        if missing:
            errors["missing_op_coverage"] = missing
        # the zero-SQL acceptance (ISSUE 14): with BucketDB routing the
        # standard mix must close with NO apply-path SQL point lookups
        # (bulk order-book scans are the write-behind index's job and
        # are counted separately)
        sql_lookups = std.get("apply_breakdown", {}) \
            .get("state_reads", {}).get("lookups", {})
        if sql_lookups:
            errors["sql_point_lookups"] = sql_lookups
    try:
        pcb = parallel_close_bench()
        out["parallel_close"] = pcb
    except Exception as e:   # noqa: BLE001 - recorded, not swallowed
        errors["parallel_close"] = repr(e)[:400]
        pcb = None
    out["replay"] = legs

    src = "bench.py --replay-full"
    records = []
    if std is not None and not errors:
        records.extend([
            bc.make_record("replay_ledgers_per_sec", "ledgers/s",
                           std["ledgers_per_sec"], "cpu-stdmix",
                           "higher", src),
            bc.make_record("replay_txs_per_sec", "txs/s",
                           std["txs_per_sec"], "cpu-stdmix", "higher",
                           src),
            bc.make_record("replay_wall_s", "s", std["wall_s"],
                           "cpu-stdmix", "lower", src),
            bc.make_record("native_bail_total", "count",
                           sum(std.get("native_bails", {}).values()),
                           "cpu-stdmix", "lower", src),
        ])
        records.extend(bc.apply_breakdown_records(
            std.get("apply_breakdown"), "cpu-stdmix", src))
    ms = legs.get("multisig")
    if ms is not None:
        records.extend([
            bc.make_record("replay_ledgers_per_sec", "ledgers/s",
                           ms["ledgers_per_sec"], "cpu-apply-native",
                           "higher", src),
            bc.make_record("replay_txs_per_sec", "txs/s",
                           ms["txs_per_sec"], "cpu-apply-native",
                           "higher", src),
        ])
    if pcb is not None:
        records.extend([
            bc.make_record("parallel_apply_speedup", "x",
                           pcb["parallel_apply_speedup"],
                           "cpu-parallel-close", "higher", src),
            bc.make_record("parallel_apply_ms", "ms",
                           pcb["parallel_apply_ms"],
                           "cpu-parallel-close", "lower", src),
            bc.make_record("serial_apply_ms", "ms",
                           pcb["serial_apply_ms"],
                           "cpu-parallel-close", "lower", src),
        ])
    out["records"] = records
    history = bc.load_history(args.history)
    report = bc.compare(records, history, tolerance=args.tolerance)
    if args.record and not errors:
        commit = _git_commit()
        now = int(time.time())
        for rec in records:
            if rec.get("at_unix") is None:
                rec["at_unix"] = now
            if rec.get("commit") is None:
                rec["commit"] = commit
        report["recorded"] = bc.append_history(args.history, records)
    out["compare"] = report
    if errors:
        out["errors"] = errors
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=1, sort_keys=True)
    print(json.dumps(out, indent=1, sort_keys=True))
    return 1 if (errors or report["regressions"]) else 0



def openssl_backend_rate(n: int = 4000) -> float:
    """Last-resort fallback: the framework's synchronous CPU backend."""
    from stellar_core_tpu.crypto.batch_verifier import CpuSigVerifier
    pubs, sigs, msgs = _example_batch(n)
    triples = list(zip(pubs, sigs, msgs))
    v = CpuSigVerifier()
    t0 = time.perf_counter()
    res = v.verify_many(triples)
    dt = time.perf_counter() - t0
    assert all(res)
    return n / dt


def main() -> None:
    t_start = time.time()
    cpu = cpu_baseline_rate()
    errors = {}

    # Relay-proof protocol (round-3/4 postmortems): probe the relay with a
    # SHORT timeout before committing to a device bench, and only run ONE
    # device process at a time. A wedged relay is detected in <=65s
    # instead of eating the whole bench budget — and instead of giving up
    # after one retry, keep re-probing on a timer for BENCH_REPROBE_S
    # seconds (default 180) in case the wedge clears mid-run.
    device_present, info = probe_device(30.0)
    if not device_present:
        errors["device_probe"] = info
        # a wedge can clear after many minutes; the headline artifact is
        # worth waiting for (r5: a stall-kill wedge cleared in ~20 min)
        reprobe_budget = float(os.environ.get("BENCH_REPROBE_S", "1500"))
        reprobe_dl = time.time() + reprobe_budget
        n_reprobes = 0
        while not device_present and time.time() < reprobe_dl:
            # a timed-out probe means a mid-init JAX client was killed —
            # which itself deepens a relay wedge — so timeout re-probes
            # are spaced WIDE; clean failures (error exit, wrong
            # platform) re-probe quickly
            wait = 150.0 if "timeout" in str(info) else 20.0
            time.sleep(min(wait, max(5.0, reprobe_dl - time.time())))
            device_present, info = probe_device(30.0)
            n_reprobes += 1
        if device_present:
            del errors["device_probe"]
            errors["device_probe_note"] = \
                "relay came back after %d re-probes" % n_reprobes
        else:
            errors["device_probe_retry"] = "%s (after %d re-probes)" % (
                info, n_reprobes)

    res = None
    replay_tpu_from_device = None
    warm_compile_s = None
    cpu_res = None
    if device_present:
        # ONE device child runs every device leg (kernel + warm compile +
        # replay), writing each stage to disk incrementally — no second
        # device process, no lost partial results on a wedge. The child is
        # killed on STALL (no partial-file flush for 600s — longer than
        # the slowest single stage, the ~100s cold compile or the replay
        # leg) rather than a flat wall, under an overall 1800s cap. A FAST
        # failure (error exit with no kernel stage on disk) is retried
        # once; a stall/wedge is not (killing a wedged JAX client wedges
        # the relay further — probe_device docstring).
        partial_path = os.path.join(_REPO, ".bench_partial.json")
        full = None
        for attempt in (1, 2):
            try:
                os.unlink(partial_path)
            except OSError:
                pass
            t_spawn = time.time()
            device_proc = _spawn_full_device_child(partial_path)
            cap = t_spawn + 1800
            stalled = False
            while device_proc.poll() is None:
                now = time.time()
                try:
                    last_flush = os.path.getmtime(partial_path)
                except OSError:
                    last_flush = t_spawn
                if now > cap or now - last_flush > 600:
                    stalled = True
                    break
                time.sleep(1.0)
            if stalled:
                device_proc.kill()
                device_proc.communicate()
                errors["device_full_bench"] = \
                    "stalled (no stage flush for 600s or >1800s total)"
            else:
                full, err = _harvest(device_proc)
                if err:
                    errors["device_attempt%d" % attempt] = err
            if full is None:
                # harvest whatever stages completed before the failure
                try:
                    with open(partial_path) as fh:
                        full = json.load(fh)
                    errors["device_partial"] = \
                        "recovered stages through %r" % \
                        full.get("last_stage_done")
                except (OSError, ValueError):
                    full = None
            if (full is not None and "rate" in full) or stalled:
                break
            # error exit before the kernel stage landed on disk: fast
            # transient — retry once
            full = None
        if full is not None and "rate" in full:
            res = full
            warm_compile_s = full.get("compile_warm_s")
            replay_tpu_from_device = full.get("replay_tpu")
            if "replay_tpu_error" in full:
                errors["replay_tpu"] = full["replay_tpu_error"]
    if res is None:
        cpu_proc = _spawn_child(_scrubbed_cpu_env(), batch=2048, iters=3)
        dl = time.time() + 300
        while time.time() < dl and cpu_proc.poll() is None:
            time.sleep(1.0)
        if cpu_proc.poll() is None:
            cpu_proc.kill()
            errors["cpu_jax"] = "killed at deadline"
        else:
            cpu_res, err = _harvest(cpu_proc)
            if err:
                errors["cpu_jax"] = err
    cache_path = os.path.join(_REPO, ".bench_device_cache.json")
    if res is not None and res.get("platform") in ("tpu", "axon"):
        # persist the kernel measurement NOW — the replay-cpu denominator
        # leg can still fail/abort, and the fresh device numbers must
        # survive it; the complete blob overwrites this at the end
        try:
            with open(cache_path, "w") as fh:
                json.dump({"at_unix": int(t_start), **res}, fh)
        except OSError:
            pass
    if res is None and cpu_res is not None:
        # No device: report the framework's best CPU-mode rate — the
        # synchronous OpenSSL backend is the default CPU path and usually
        # beats the jax-on-CPU kernel, which exists for TPUs.
        rate = openssl_backend_rate()
        if rate > cpu_res["rate"]:
            cpu_res = {"rate": rate, "platform": "openssl-cpu-backend",
                       "batch": 4000, "init_s": 0.0, "compile_s": 0.0}
        res = cpu_res
    cached_device = None
    if res is None or res.get("platform") not in ("tpu", "axon"):
        # a device-less run still surfaces the last COMPLETE device
        # measurement (kernel + warm compile + replay ratios) — promoted
        # to the top-level `last_device` block below, not buried in
        # errors.* (ISSUE 1: the r5 headline was a 16x-low OpenSSL
        # fallback that misled consumers who didn't read errors)
        try:
            with open(cache_path) as fh:
                cached_device = json.load(fh)
        except (OSError, ValueError):
            pass

    out = {
        "metric": "ed25519_verifies_per_sec_per_chip",
        "unit": "sigs/s",
        "cpu_openssl_baseline_sigs_per_sec": round(cpu, 1),
    }
    if res is not None:
        out["value"] = round(res["rate"], 1)
        out["vs_baseline"] = round(res["rate"] / cpu, 3)
        out["platform"] = res["platform"]
        out["batch"] = res["batch"]
        out["init_s"] = res["init_s"]
        out["compile_s"] = res["compile_s"]
        if warm_compile_s is not None:
            out["compile_warm_s"] = warm_compile_s
        for k in ("latency128_p50_ms", "latency128_p99_ms",
                  "warmup_state", "warmup_buckets_s", "compile_cache"):
            if k in res:
                out[k] = res[k]
    else:
        # Last resort: framework's synchronous OpenSSL backend.
        rate = openssl_backend_rate()
        out["value"] = round(rate, 1)
        out["vs_baseline"] = round(rate / cpu, 3)
        out["platform"] = "openssl-fallback"
    # --- second north star: catchup-replay speedup (tpu vs cpu backend) ---
    # the tpu leg already ran inside the single device child; only the cpu
    # DENOMINATOR leg runs here, in a scrubbed child that never touches
    # the relay. Run it SEQUENTIALLY (nothing else live): concurrent
    # children contend for the same cores and contaminate the timing.
    have_tpu = res is not None and res.get("platform") in ("tpu", "axon")
    rep_tpu = replay_tpu_from_device if have_tpu else None
    rep_cpu = None
    if rep_tpu is not None:
        proc = _spawn_replay(_scrubbed_cpu_env(), "cpu")
        rep_deadline = time.time() + 420
        while time.time() < rep_deadline and proc.poll() is None:
            time.sleep(1.0)
        if proc.poll() is None:
            proc.kill()
            errors["replay_cpu"] = "killed at deadline"
        else:
            rep_cpu, err = _harvest(proc, "REPLAY_JSON")
            if err:
                errors["replay_cpu"] = err
    elif not have_tpu:
        # a jax-on-CPU "tpu" run would report a misleadingly tiny ratio,
        # and a cpu-only leg can't produce one either — skip both and
        # record why the field is absent
        errors.setdefault("replay_tpu", "no TPU device this run; "
                                        "ratio skipped")
        # …but the backend-independent APPLY cost is measurable without a
        # device (ISSUE 1 acceptance: record it either way): same CPU
        # replay leg with the native apply engine on vs pinned to the
        # Python path; apply cost = wall minus the crypto drain
        rep = {}
        for label, toggle in (("native", "1"), ("python", "0")):
            env = _scrubbed_cpu_env()
            env["SCT_NATIVE_APPLY"] = toggle
            proc = _spawn_replay(env, "cpu")
            deadline = time.time() + 420
            while time.time() < deadline and proc.poll() is None:
                time.sleep(1.0)
            if proc.poll() is None:
                proc.kill()
                errors["replay_apply_" + label] = "killed at deadline"
                continue
            r, err = _harvest(proc, "REPLAY_JSON")
            if err:
                errors["replay_apply_" + label] = err
            else:
                r["apply_s"] = round(r["wall_s"] - r["crypto_s"], 3)
                rep[label] = r
        if "native" in rep and "python" in rep:
            out["replay_apply"] = {
                **rep,
                "apply_speedup": round(
                    rep["python"]["apply_s"] / rep["native"]["apply_s"], 3),
            }
    if rep_cpu is not None and rep_tpu is not None:
        out["replay"] = {"cpu": rep_cpu, "tpu": rep_tpu}
        out["replay_speedup"] = round(
            rep_tpu["ledgers_per_sec"] / rep_cpu["ledgers_per_sec"], 3)
        if rep_tpu.get("crypto_s"):
            # crypto-subsystem drain ratio (whole-checkpoint batch path):
            # same replay, time inside the signature drain only
            out["replay_crypto_speedup"] = round(
                rep_cpu["crypto_s"] / rep_tpu["crypto_s"], 3)

    # top-level `last_device`: ALWAYS the most recent real device
    # measurement — fresh when this run reached a device, the cached blob
    # (stamped with its capture time and cached=true) when it didn't. A
    # consumer reading only the headline can no longer mistake an
    # OpenSSL-fallback `value` for device numbers.
    if out.get("platform") in ("tpu", "axon"):
        out["last_device"] = {
            "at_unix": int(t_start), "cached": False,
            **{k: out[k] for k in
               ("value", "vs_baseline", "platform", "replay_speedup",
                "replay_crypto_speedup", "compile_cache",
                "warmup_buckets_s") if k in out}}
    elif cached_device is not None:
        out["last_device"] = {"cached": True, **cached_device}

    if errors:
        out["errors"] = errors
    if out.get("platform") in ("tpu", "axon"):
        # cache the COMPLETE successful device measurement (incl. replay
        # legs) so a later wedged-relay run can still surface it
        try:
            blob = {k: v for k, v in out.items() if k != "last_device"}
            with open(cache_path, "w") as fh:
                json.dump({"at_unix": int(t_start), **blob}, fh)
        except OSError:
            pass
    print(json.dumps(out))


if __name__ == "__main__":
    if "--chaos" in sys.argv:
        # chaos smoke leg: close-latency p95 with faults on vs off; does
        # not touch jax or the device relay
        print(json.dumps(chaos_smoke()))
    elif "--fleet" in sys.argv:
        # multi-node leg: 3-node consensus with merged timelines; emits
        # the `fleet` block (slot-latency p50/p95, externalize skew);
        # does not touch jax or the device relay
        print(json.dumps(fleet_bench()))
    elif "--fleet-scale" in sys.argv:
        # N-vs-cost scaling leg (ISSUE 19): 10/25/50-node sims under a
        # three-region latency matrix; per-node RSS, externalize skew
        # p95, envelopes per slot, gated against bench/history.jsonl;
        # does not touch jax or the device relay
        sys.exit(fleet_scale_main(sys.argv[1:]))
    elif "--fleet-verify" in sys.argv:
        # multi-device verify leg (ISSUE 11): sharded drains on forced
        # virtual-CPU fleets, gated against bench/history.jsonl; spawns
        # scrubbed CPU children only — never touches the device relay
        sys.exit(fleet_verify_main(sys.argv[1:]))
    elif "--hash" in sys.argv:
        # batched-hashing leg (ISSUE 12): kernel throughput per bucket
        # shape in a scrubbed CPU child + inline checkpoint/light-client
        # leg + CPU replay phase evidence; gated against
        # bench/history.jsonl; never touches the device relay
        sys.exit(hash_main(sys.argv[1:]))
    elif "--replay-full" in sys.argv:
        # full-coverage apply leg (ISSUE 13): standard-mix zero-bail
        # replay + legacy multisig replay + the parallel-close gate;
        # scrubbed CPU children only — never touches the device relay
        sys.exit(replay_full_main(sys.argv[1:]))
    elif "--bucketdb" in sys.argv:
        # million-account BucketDB leg (ISSUE 14): close-latency
        # flatness from 10^4 to 10^6 seeded accounts over bucket-backed
        # reads, surge prefetch hit-rate, bloom FP rate, zero-SQL gate;
        # pure CPU/IO — does not touch jax or the device relay
        sys.exit(bucketdb_main(sys.argv[1:]))
    elif "--scenario" in sys.argv:
        # scenario lab (ISSUE 8): churn / flood / partition / surge
        # robustness scenarios emitting fleet bench blocks gated against
        # bench/history.jsonl; does not touch jax or the device relay
        sys.exit(scenario_main(sys.argv[1:]))
    elif "--compare" in sys.argv:
        # perf-regression gate against bench/history.jsonl; does not
        # touch jax or the device relay
        sys.exit(compare_main(sys.argv[1:]))
    else:
        main()
