"""Benchmark: batched ed25519 verify throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline = measured TPU rate / single-core CPU (OpenSSL) rate — the
reference's implicit baseline is single-call libsodium verify
(BASELINE.md; reference crypto bench harness src/crypto/test/
CryptoTests.cpp:235-258). The north-star target is >=100K verifies/s/chip.
"""

from __future__ import annotations

import json
import sys
import time


def cpu_baseline_rate(n: int = 2000) -> float:
    from stellar_core_tpu.crypto.keys import raw_verify
    from stellar_core_tpu.models.verifier_model import make_example_batch
    pubs, sigs, msgs = make_example_batch(batch=n, n_keys=32)
    t0 = time.perf_counter()
    ok = True
    for p, s, m in zip(pubs, sigs, msgs):
        ok &= raw_verify(p, s, m)
    dt = time.perf_counter() - t0
    assert ok
    return n / dt


def tpu_rate(batch: int = 4096, iters: int = 5) -> float:
    import jax.numpy as jnp
    from stellar_core_tpu.models.verifier_model import (
        device_args, make_example_batch,
    )
    from stellar_core_tpu.ops.ed25519 import verify_batch_jit
    pubs, sigs, msgs = make_example_batch(batch=batch, n_keys=64)
    args = device_args(pubs, sigs, msgs)
    # compile + correctness gate
    ok = verify_batch_jit(*args)
    ok.block_until_ready()
    assert bool(ok.all()), "verify kernel rejected valid signatures"
    best = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        verify_batch_jit(*args).block_until_ready()
        dt = time.perf_counter() - t0
        best = max(best, batch / dt)
    return best


def main() -> None:
    try:
        import jax
        platform = jax.devices()[0].platform
    except Exception as e:
        print(json.dumps({
            "metric": "ed25519_verifies_per_sec_per_chip",
            "value": 0, "unit": "sigs/s", "vs_baseline": 0.0,
            "error": "device init failed: %s" % type(e).__name__}))
        return
    cpu = cpu_baseline_rate()
    dev = tpu_rate()
    print(json.dumps({
        "metric": "ed25519_verifies_per_sec_per_chip",
        "value": round(dev, 1),
        "unit": "sigs/s",
        "vs_baseline": round(dev / cpu, 3),
        "cpu_openssl_baseline_sigs_per_sec": round(cpu, 1),
        "platform": platform,
    }))


if __name__ == "__main__":
    main()
